"""Post-training quantization (reference
`contrib/slim/quantization/post_training_quantization.py`)."""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from .. import nn
from ..nn import functional as F  # noqa: F401  (kept for subclasses)


class AbsmaxQuantizer:
    def __init__(self):
        self.max = 0.0

    def observe(self, arr):
        self.max = max(self.max, float(np.max(np.abs(arr))))

    def scale(self):
        return max(self.max, 1e-8)


class HistQuantizer:
    """Percentile-clipped range (the reference's `hist` method,
    `post_training_quantization.py` hist_percent)."""

    def __init__(self, percentile=99.99, bins=2048):
        self.percentile = percentile
        self.vals = []

    def observe(self, arr):
        self.vals.append(np.abs(np.asarray(arr)).ravel())

    def scale(self):
        if not self.vals:
            return 1e-8
        allv = np.concatenate(self.vals)
        return max(float(np.percentile(allv, self.percentile)), 1e-8)


class KLQuantizer:
    """KL-divergence threshold calibration (the reference's `KL` method,
    `post_training_quantization.py` _sample_data KL path /
    `cal_kl_threshold.py`, the TensorRT-style algorithm): build a
    2048-bin |x| histogram, then pick the clip threshold whose
    128-level quantized distribution has minimum KL divergence from the
    clipped reference distribution."""

    def __init__(self, bins=2048, quant_bins=128):
        self.bins = bins
        self.quant_bins = quant_bins
        self.hist = None
        self.hist_max = None

    def observe(self, arr):
        a = np.abs(np.asarray(arr, np.float64)).ravel()
        amax = float(a.max()) if a.size else 0.0
        if amax == 0.0:
            return
        if self.hist is None:
            self.hist_max = amax
            self.hist, _ = np.histogram(a, bins=self.bins,
                                        range=(0, self.hist_max))
            self.hist = self.hist.astype(np.float64)
        else:
            if amax > self.hist_max:
                # stretch: rebin the existing histogram onto a wider range
                old_edges = np.linspace(0, self.hist_max, self.bins + 1)
                centers = (old_edges[:-1] + old_edges[1:]) / 2
                new_hist = np.zeros(self.bins)
                idx = np.minimum((centers / amax * self.bins).astype(int),
                                 self.bins - 1)
                np.add.at(new_hist, idx, self.hist)
                self.hist, self.hist_max = new_hist, amax
            h, _ = np.histogram(a, bins=self.bins, range=(0, self.hist_max))
            self.hist += h

    @staticmethod
    def _kl(p, q):
        p = p / max(p.sum(), 1e-12)
        q = q / max(q.sum(), 1e-12)
        mask = p > 0
        qm = np.where(q > 0, q, 1e-12)
        return float(np.sum(p[mask] * np.log(p[mask] / qm[mask])))

    def scale(self):
        if self.hist is None:
            return 1e-8
        best_kl, best_i = None, self.bins
        for i in range(self.quant_bins, self.bins + 1, self.quant_bins // 2):
            p = self.hist[:i].copy()
            p[i - 1] += self.hist[i:].sum()     # clip outliers into edge
            # candidate Q: the in-range histogram (WITHOUT the clipped
            # outlier mass — else i == quant_bins is trivially KL=0)
            # quantized to quant_bins levels and expanded back, mass
            # spread only over originally-nonzero bins
            src = self.hist[:i]
            q = np.zeros(i)
            chunk = i / self.quant_bins
            for j in range(self.quant_bins):
                lo, hi = int(round(j * chunk)), int(round((j + 1) * chunk))
                seg = src[lo:hi]
                nz = seg > 0
                if nz.any():
                    q[lo:hi][nz] = seg.sum() / nz.sum()
            kl = self._kl(p, q)
            if best_kl is None or kl < best_kl:
                best_kl, best_i = kl, i
        return max(best_i / self.bins * self.hist_max, 1e-8)


class Int8Linear(nn.Layer):
    """Real-int8 inference linear: w stored int8 with PER-OUTPUT-CHANNEL
    scales (reference `channel_wise_abs_max`, `quantization_pass.py`),
    activations quantized at the boundary, i8 x i8 -> i32 dot on the MXU
    (2x bf16 throughput on v5e+), dequant fused by XLA."""

    def __init__(self, layer, act_scale, bits=8, per_channel=True):
        super().__init__()
        qmax = 2.0 ** (bits - 1) - 1
        w = layer.weight.numpy()                 # [in, out]
        if per_channel:
            ws = np.maximum(np.max(np.abs(w), axis=0), 1e-8)  # [out]
        else:
            ws = np.full((w.shape[1],), max(float(np.max(np.abs(w))),
                                            1e-8), np.float32)
        self.w_scale = Tensor(jnp.asarray(ws, jnp.float32),
                              stop_gradient=True)
        self.wq = Tensor(jnp.asarray(
            np.clip(np.round(w / ws * qmax), -qmax, qmax), jnp.int8),
            stop_gradient=True)
        self.bias = layer.bias
        self.act_scale = float(act_scale)
        self.qmax = qmax

    def forward(self, x):
        s_in, qmax = self.act_scale, self.qmax

        def fn(xv, wq, ws, *maybe_bias):
            xq = jnp.clip(jnp.round(xv / s_in * qmax), -qmax, qmax
                          ).astype(jnp.int8)
            out = jnp.matmul(xq, wq, preferred_element_type=jnp.int32)
            out = out.astype(jnp.float32) * (s_in * ws / (qmax * qmax))
            if maybe_bias:
                out = out + maybe_bias[0]
            return out
        args = (x, self.wq, self.w_scale) + (
            (self.bias,) if self.bias is not None else ())
        return apply(fn, *args)


class Int8Conv2D(nn.Layer):
    """Real-int8 inference conv with per-output-channel weight scales;
    i8 x i8 -> i32 on the MXU convolution path."""

    def __init__(self, layer, act_scale, bits=8, per_channel=True):
        super().__init__()
        qmax = 2.0 ** (bits - 1) - 1
        w = layer.weight.numpy()                 # [out, in, kh, kw]
        if per_channel:
            ws = np.maximum(np.max(np.abs(w), axis=(1, 2, 3)), 1e-8)
        else:
            ws = np.full((w.shape[0],), max(float(np.max(np.abs(w))),
                                            1e-8), np.float32)
        self.w_scale = Tensor(jnp.asarray(ws, jnp.float32),
                              stop_gradient=True)
        self.wq = Tensor(jnp.asarray(
            np.clip(np.round(w / ws[:, None, None, None] * qmax),
                    -qmax, qmax), jnp.int8), stop_gradient=True)
        self.bias = layer.bias
        self.act_scale = float(act_scale)
        self.qmax = qmax
        self._stride = layer._stride
        self._padding = layer._padding
        self._dilation = layer._dilation
        self._groups = layer._groups

    def forward(self, x):
        s_in, qmax = self.act_scale, self.qmax
        stride, padding = self._stride, self._padding
        dilation, groups = self._dilation, self._groups

        def fn(xv, wq, ws, *maybe_bias):
            from ..nn.functional.conv import _norm_padding
            xq = jnp.clip(jnp.round(xv / s_in * qmax), -qmax, qmax
                          ).astype(jnp.int8)
            pad = _norm_padding(padding, 2)
            out = jax.lax.conv_general_dilated(
                xq, wq, window_strides=tuple(stride), padding=pad,
                rhs_dilation=tuple(dilation),
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=groups,
                preferred_element_type=jnp.int32)
            out = out.astype(jnp.float32) * (
                s_in * ws[None, :, None, None] / (qmax * qmax))
            if maybe_bias:
                out = out + maybe_bias[0][None, :, None, None]
            return out
        args = (x, self.wq, self.w_scale) + (
            (self.bias,) if self.bias is not None else ())
        return apply(fn, *args)


def iter_conv_bn_pairs(model):
    """Yield (container, conv_name, conv, bn_name, bn) for each adjacent
    (Conv2D, BatchNorm) pair inside Sequential containers — the shared
    pair scan under both PTQ folding and BN-fold QAT."""
    for layer in [model] + [m for _, m in model.named_sublayers()]:
        if type(layer).__name__ != "Sequential":
            continue
        items = list(layer._sub_layers.items())
        for (n1, c1), (n2, c2) in zip(items, items[1:]):
            if type(c1).__name__ == "Conv2D" and \
                    type(c2).__name__ in ("BatchNorm2D", "BatchNorm"):
                yield layer, n1, c1, n2, c2


def fold_conv_bn(model):
    """Fold BatchNorm layers into the immediately preceding Conv2D inside
    Sequential containers (reference `conv_bn_fuse_pass.cc` /
    quantization BN folding): w' = w * g/sqrt(v+eps) per out-channel,
    b' = beta + (b - mean) * g/sqrt(v+eps). Returns #folds."""
    folded = 0
    for layer, n1, c1, n2, c2 in iter_conv_bn_pairs(model):
        g = c2.weight.numpy() if c2.weight is not None else \
            np.ones(c1.weight.shape[0], np.float32)
        beta = c2.bias.numpy() if c2.bias is not None else \
            np.zeros(c1.weight.shape[0], np.float32)
        mean = c2._mean.numpy()
        var = c2._variance.numpy()
        f = g / np.sqrt(var + c2._epsilon)
        w = c1.weight.numpy() * f[:, None, None, None]
        b = (c1.bias.numpy() if c1.bias is not None
             else np.zeros_like(mean))
        b = beta + (b - mean) * f
        c1.weight._value = jnp.asarray(w, jnp.float32)
        if c1.bias is None:
            c1.bias = c1.create_parameter([w.shape[0]], is_bias=True)
        c1.bias._value = jnp.asarray(b, jnp.float32)
        from ..nn import Identity
        layer._sub_layers[n2] = Identity()
        folded += 1
    return folded


class PTQ:
    """Calibrate activation ranges over sample batches, then convert
    Linear/Conv2D layers to real-int8 inference layers.

    quantizer: "abs_max" | "hist" (percentile) | "KL" (divergence
    threshold search) — the reference's algo names
    (`post_training_quantization.py` activation_quantize_type).
    weight_quantize_type: "channel_wise_abs_max" (default) | "abs_max".
    fold_bn: fold BatchNorm into preceding convs before quantizing, the
    reference's conv+BN fuse precondition for int8 deploy."""

    def __init__(self, quantizer="abs_max", bits=8,
                 weight_quantize_type="channel_wise_abs_max",
                 fold_bn=True):
        self.bits = bits
        self.quantizer = quantizer
        self.per_channel = weight_quantize_type == "channel_wise_abs_max"
        self.fold_bn = fold_bn
        self._observers = {}

    def _make_q(self):
        if self.quantizer == "KL":
            return KLQuantizer()
        if self.quantizer == "hist":
            return HistQuantizer()
        return AbsmaxQuantizer()

    _QUANTIZABLE = ("Linear", "Conv2D")

    def quantize(self, model, calib_fn=None, calib_data=None):
        """Attach observers, run calibration data, convert in place."""
        if self.fold_bn:
            fold_conv_bn(model)
        hooks = []
        observers = {}

        def attach(layer):
            for name, child in list(layer._sub_layers.items()):
                if type(child).__name__ in self._QUANTIZABLE:
                    q = self._make_q()
                    observers[id(child)] = q
                    hooks.append(child.register_forward_pre_hook(
                        lambda lyr, inputs, _q=q: _q.observe(
                            inputs[0].numpy())))
                else:
                    attach(child)
        attach(model)
        model.eval()
        if calib_fn is not None:
            calib_fn(model)
        elif calib_data is not None:
            from ..core import autograd
            with autograd.no_grad():
                for batch in calib_data:
                    batch = batch if isinstance(batch, (list, tuple)) \
                        else [batch]
                    model(*[b if isinstance(b, Tensor) else Tensor(b)
                            for b in batch])
        for h in hooks:
            h.remove()

        def convert(layer):
            for name, child in list(layer._sub_layers.items()):
                if id(child) in observers:
                    cls = (Int8Linear if type(child).__name__ == "Linear"
                           else Int8Conv2D)
                    layer._sub_layers[name] = cls(
                        child, observers[id(child)].scale(), self.bits,
                        per_channel=self.per_channel)
                else:
                    convert(child)
        convert(model)
        return model
