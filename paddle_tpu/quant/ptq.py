"""Post-training quantization (reference
`contrib/slim/quantization/post_training_quantization.py`)."""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from .. import nn
from ..nn import functional as F


class AbsmaxQuantizer:
    def __init__(self):
        self.max = 0.0

    def observe(self, arr):
        self.max = max(self.max, float(np.max(np.abs(arr))))

    def scale(self):
        return max(self.max, 1e-8)


class HistQuantizer:
    """Percentile-clipped range (cheap stand-in for the reference's KL
    calibration)."""

    def __init__(self, percentile=99.99, bins=2048):
        self.percentile = percentile
        self.vals = []

    def observe(self, arr):
        self.vals.append(np.abs(np.asarray(arr)).ravel())

    def scale(self):
        if not self.vals:
            return 1e-8
        allv = np.concatenate(self.vals)
        return max(float(np.percentile(allv, self.percentile)), 1e-8)


class Int8Linear(nn.Layer):
    """Real-int8 inference linear: w stored int8, activations quantized at
    the boundary, i8 x i8 -> i32 dot on the MXU, dequant fused by XLA."""

    def __init__(self, layer, act_scale, bits=8):
        super().__init__()
        qmax = 2.0 ** (bits - 1) - 1
        w = layer.weight.numpy()
        self.w_scale = float(np.max(np.abs(w)) or 1e-8)
        self.wq = Tensor(jnp.asarray(
            np.clip(np.round(w / self.w_scale * qmax), -qmax, qmax),
            jnp.int8), stop_gradient=True)
        self.bias = layer.bias
        self.act_scale = float(act_scale)
        self.qmax = qmax

    def forward(self, x):
        s_in, s_w, qmax = self.act_scale, self.w_scale, self.qmax

        def fn(xv, wq, *maybe_bias):
            xq = jnp.clip(jnp.round(xv / s_in * qmax), -qmax, qmax
                          ).astype(jnp.int8)
            out = jnp.matmul(xq, wq, preferred_element_type=jnp.int32)
            out = out.astype(jnp.float32) * (s_in * s_w / (qmax * qmax))
            if maybe_bias:
                out = out + maybe_bias[0]
            return out
        args = (x, self.wq) + ((self.bias,) if self.bias is not None else ())
        return apply(fn, *args)


class PTQ:
    """Calibrate activation ranges over sample batches, then convert
    Linear layers to real-int8 inference layers."""

    def __init__(self, quantizer="abs_max", bits=8):
        self.bits = bits
        self.quantizer = quantizer
        self._observers = {}

    def _make_q(self):
        return (HistQuantizer() if self.quantizer in ("hist", "KL")
                else AbsmaxQuantizer())

    def quantize(self, model, calib_fn=None, calib_data=None):
        """Attach observers, run calibration data, convert in place."""
        hooks = []
        observers = {}

        def attach(layer):
            for name, child in list(layer._sub_layers.items()):
                if type(child).__name__ == "Linear":
                    q = self._make_q()
                    observers[id(child)] = q

                    def hook(lyr, inputs, _q=q):
                        x = inputs[0]
                        _q.observe(x.numpy())
                    hooks.append(child.register_forward_pre_hook(
                        lambda lyr, inputs, _q=q: _q.observe(
                            inputs[0].numpy())))
                else:
                    attach(child)
        attach(model)
        model.eval()
        if calib_fn is not None:
            calib_fn(model)
        elif calib_data is not None:
            from ..core import autograd
            with autograd.no_grad():
                for batch in calib_data:
                    batch = batch if isinstance(batch, (list, tuple)) \
                        else [batch]
                    model(*[b if isinstance(b, Tensor) else Tensor(b)
                            for b in batch])
        for h in hooks:
            h.remove()

        def convert(layer):
            for name, child in list(layer._sub_layers.items()):
                if id(child) in observers:
                    layer._sub_layers[name] = Int8Linear(
                        child, observers[id(child)].scale(), self.bits)
                else:
                    convert(child)
        convert(model)
        return model
