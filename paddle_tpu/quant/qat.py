"""Quantization-aware training (imperative QAT analog,
`contrib/slim/quantization/imperative/qat.py`)."""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from .. import nn
from ..nn import functional as F


def quant_dequant(x, scale, bits=8):
    """Fake-quantize with straight-through gradient:
    y = x + stop_grad(q(x) - x)."""
    qmax = 2.0 ** (bits - 1) - 1

    def fn(v, s):
        s = jnp.maximum(s, 1e-8)
        q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax) * s / qmax
        return v + jax.lax.stop_gradient(q - v)
    return apply(fn, x, scale)


def quant_dequant_channelwise(w, bits=8, axis=-1):
    """Per-channel fake-quant over `axis` (reference
    `channel_wise_abs_max` fake-quant op), straight-through grads."""
    qmax = 2.0 ** (bits - 1) - 1

    def fn(v):
        red = tuple(i for i in range(v.ndim) if i != axis % v.ndim)
        s = jnp.maximum(jnp.max(jnp.abs(v), axis=red, keepdims=True),
                        1e-8)
        q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax) * s / qmax
        return v + jax.lax.stop_gradient(q - v)
    return apply(fn, w)


class FakeQuantAbsMax(nn.Layer):
    """Running abs-max observer + fake quant (the moving-average absmax
    quantizer of `quantization_pass.py`)."""

    def __init__(self, bits=8, momentum=0.9):
        super().__init__()
        self.bits = bits
        self.momentum = momentum
        self.scale = self.create_buffer([1], fill=1e-8)

    def create_buffer(self, shape, fill):
        t = Tensor(jnp.full(shape, fill, jnp.float32), stop_gradient=True)
        self.register_buffer("scale_buf", t)
        return t

    def forward(self, x):
        if self.training:
            cur = apply(lambda v: jnp.max(jnp.abs(v)).reshape(1), x)
            m = self.momentum
            new_scale = apply(
                lambda s, c: jnp.maximum(m * s + (1 - m) * c, 1e-8),
                self.scale, cur)
            self.scale._value = jax.lax.stop_gradient(new_scale._value)
        return quant_dequant(x, self.scale, self.bits)


class QuantizedLinear(nn.Layer):
    def __init__(self, layer, bits=8, per_channel=True):
        super().__init__()
        self.inner = layer
        self.act_quant = FakeQuantAbsMax(bits)
        self.w_quant_bits = bits
        self.per_channel = per_channel

    def forward(self, x):
        x = self.act_quant(x)
        w = self.inner.weight
        if self.per_channel:
            wq = quant_dequant_channelwise(w, self.w_quant_bits, axis=1)
        else:
            w_scale = apply(lambda v: jnp.max(jnp.abs(v)).reshape(1), w)
            wq = quant_dequant(w, w_scale, self.w_quant_bits)
        out = F.linear(x, wq, self.inner.bias)
        return out


class QuantizedConv2D(nn.Layer):
    def __init__(self, layer, bits=8, per_channel=True):
        super().__init__()
        self.inner = layer
        self.act_quant = FakeQuantAbsMax(bits)
        self.w_quant_bits = bits
        self.per_channel = per_channel

    def forward(self, x):
        x = self.act_quant(x)
        w = self.inner.weight
        if self.per_channel:
            wq = quant_dequant_channelwise(w, self.w_quant_bits, axis=0)
        else:
            w_scale = apply(lambda v: jnp.max(jnp.abs(v)).reshape(1), w)
            wq = quant_dequant(w, w_scale, self.w_quant_bits)
        return F.conv2d(x, wq, self.inner.bias,
                        stride=self.inner._stride,
                        padding=self.inner._padding,
                        dilation=self.inner._dilation,
                        groups=self.inner._groups)


class QuantizedConv2DBN(nn.Layer):
    """BN-fold QAT (reference `quantization_pass.py` _fold / Jacob et
    al. frozen-stats fold): the conv weight is folded with the BN's
    RUNNING stats, fake-quantized per-channel, and applied in one conv —
    so training sees exactly the arithmetic int8 deployment will use.
    The wrapped BN still updates its running stats from the pre-fold
    conv output while training."""

    def __init__(self, conv, bn, bits=8, per_channel=True):
        super().__init__()
        self.conv = conv
        self.bn = bn
        self.act_quant = FakeQuantAbsMax(bits)
        self.w_quant_bits = bits
        self.per_channel = per_channel
        # affine-less BN (weight_attr/bias_attr=False): fold with
        # constant gamma=1 / beta=0, same guard as ptq.fold_conv_bn
        nf = bn._mean.shape[0]
        self._gamma = bn.weight if bn.weight is not None else Tensor(
            jnp.ones([nf], jnp.float32), stop_gradient=True)
        self._beta = bn.bias if bn.bias is not None else Tensor(
            jnp.zeros([nf], jnp.float32), stop_gradient=True)

    def _folded_wb(self):
        g = self._gamma
        beta = self._beta
        mean, var = self.bn._mean, self.bn._variance
        eps = self.bn._epsilon

        def fold_w(w, gv, vv):
            f = gv / jnp.sqrt(vv + eps)
            return w * f[:, None, None, None]

        def fold_b(b, gv, bv, mv, vv):
            f = gv / jnp.sqrt(vv + eps)
            return bv + (b - mv) * f
        w = apply(fold_w, self.conv.weight, g, var)
        bias = self.conv.bias
        if bias is None:
            zero = Tensor(jnp.zeros(mean.shape, jnp.float32),
                          stop_gradient=True)
            bias = zero
        b = apply(fold_b, bias, g, beta, mean, var)
        return w, b

    def forward(self, x):
        x = self.act_quant(x)
        w, b = self._folded_wb()
        if self.per_channel:
            wq = quant_dequant_channelwise(w, self.w_quant_bits, axis=0)
        else:
            ws = apply(lambda v: jnp.max(jnp.abs(v)).reshape(1), w)
            wq = quant_dequant(w, ws, self.w_quant_bits)
        out = F.conv2d(x, wq, b, stride=self.conv._stride,
                       padding=self.conv._padding,
                       dilation=self.conv._dilation,
                       groups=self.conv._groups)
        if self.training:
            # keep the running stats live: a shadow unfolded conv output
            # feeds the BN update, its normalized result is discarded
            from ..core import autograd
            with autograd.no_grad():
                raw = F.conv2d(x, self.conv.weight, self.conv.bias,
                               stride=self.conv._stride,
                               padding=self.conv._padding,
                               dilation=self.conv._dilation,
                               groups=self.conv._groups)
                self.bn(raw)
        return out


class QAT:
    """`QAT().quantize(model)` swaps Linear/Conv2D sublayers in place for
    fake-quant wrappers (imperative QAT `qat.py` ImperativeQuantAware).
    With fold_bn=True, (Conv2D, BatchNorm) pairs inside Sequential
    containers become one BN-fold QAT layer (QuantizedConv2DBN)."""

    def __init__(self, bits=8, quantizable_layer_type=("Linear", "Conv2D"),
                 per_channel=True, fold_bn=False):
        self.bits = bits
        self.types = set(quantizable_layer_type)
        self.per_channel = per_channel
        self.fold_bn = fold_bn

    def quantize(self, model):
        if self.fold_bn:
            self._fold_pairs(model)
        self._swap(model)
        return model

    def _fold_pairs(self, model):
        from ..nn import Identity
        from .ptq import iter_conv_bn_pairs
        for layer, n1, c1, n2, c2 in iter_conv_bn_pairs(model):
            layer._sub_layers[n1] = QuantizedConv2DBN(
                c1, c2, self.bits, self.per_channel)
            layer._sub_layers[n2] = Identity()

    def _swap(self, layer):
        for name, child in list(layer._sub_layers.items()):
            cls = type(child).__name__
            if cls.startswith("Quantized") or cls.startswith("Int8"):
                continue            # already wrapped (e.g. BN-fold pair)
            if cls == "Linear" and "Linear" in self.types:
                layer._sub_layers[name] = QuantizedLinear(
                    child, self.bits, self.per_channel)
            elif cls == "Conv2D" and "Conv2D" in self.types:
                layer._sub_layers[name] = QuantizedConv2D(
                    child, self.bits, self.per_channel)
            else:
                self._swap(child)

    def save_quantized_model(self, model, path, input_spec=None):
        from ..inference.export import save_inference_model
        model.eval()
        return save_inference_model(path, model, input_spec=input_spec)
