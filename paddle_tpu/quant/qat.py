"""Quantization-aware training (imperative QAT analog,
`contrib/slim/quantization/imperative/qat.py`)."""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from .. import nn
from ..nn import functional as F


def quant_dequant(x, scale, bits=8):
    """Fake-quantize with straight-through gradient:
    y = x + stop_grad(q(x) - x)."""
    qmax = 2.0 ** (bits - 1) - 1

    def fn(v, s):
        s = jnp.maximum(s, 1e-8)
        q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax) * s / qmax
        return v + jax.lax.stop_gradient(q - v)
    return apply(fn, x, scale)


class FakeQuantAbsMax(nn.Layer):
    """Running abs-max observer + fake quant (the moving-average absmax
    quantizer of `quantization_pass.py`)."""

    def __init__(self, bits=8, momentum=0.9):
        super().__init__()
        self.bits = bits
        self.momentum = momentum
        self.scale = self.create_buffer([1], fill=1e-8)

    def create_buffer(self, shape, fill):
        t = Tensor(jnp.full(shape, fill, jnp.float32), stop_gradient=True)
        self.register_buffer("scale_buf", t)
        return t

    def forward(self, x):
        if self.training:
            cur = apply(lambda v: jnp.max(jnp.abs(v)).reshape(1), x)
            m = self.momentum
            new_scale = apply(
                lambda s, c: jnp.maximum(m * s + (1 - m) * c, 1e-8),
                self.scale, cur)
            self.scale._value = jax.lax.stop_gradient(new_scale._value)
        return quant_dequant(x, self.scale, self.bits)


class QuantizedLinear(nn.Layer):
    def __init__(self, layer, bits=8):
        super().__init__()
        self.inner = layer
        self.act_quant = FakeQuantAbsMax(bits)
        self.w_quant_bits = bits

    def forward(self, x):
        x = self.act_quant(x)
        w = self.inner.weight
        w_scale = apply(lambda v: jnp.max(jnp.abs(v)).reshape(1), w)
        wq = quant_dequant(w, w_scale, self.w_quant_bits)
        out = F.linear(x, wq, self.inner.bias)
        return out


class QuantizedConv2D(nn.Layer):
    def __init__(self, layer, bits=8):
        super().__init__()
        self.inner = layer
        self.act_quant = FakeQuantAbsMax(bits)
        self.w_quant_bits = bits

    def forward(self, x):
        x = self.act_quant(x)
        w = self.inner.weight
        w_scale = apply(lambda v: jnp.max(jnp.abs(v)).reshape(1), w)
        wq = quant_dequant(w, w_scale, self.w_quant_bits)
        return F.conv2d(x, wq, self.inner.bias,
                        stride=self.inner._stride,
                        padding=self.inner._padding,
                        dilation=self.inner._dilation,
                        groups=self.inner._groups)


class QAT:
    """`QAT().quantize(model)` swaps Linear/Conv2D sublayers in place for
    fake-quant wrappers (imperative QAT `qat.py` ImperativeQuantAware)."""

    def __init__(self, bits=8, quantizable_layer_type=("Linear", "Conv2D")):
        self.bits = bits
        self.types = set(quantizable_layer_type)

    def quantize(self, model):
        self._swap(model)
        return model

    def _swap(self, layer):
        for name, child in list(layer._sub_layers.items()):
            cls = type(child).__name__
            if cls == "Linear" and "Linear" in self.types:
                layer._sub_layers[name] = QuantizedLinear(child, self.bits)
            elif cls == "Conv2D" and "Conv2D" in self.types:
                layer._sub_layers[name] = QuantizedConv2D(child, self.bits)
            else:
                self._swap(child)

    def save_quantized_model(self, model, path, input_spec=None):
        from ..inference.export import save_inference_model
        model.eval()
        return save_inference_model(path, model, input_spec=input_spec)
