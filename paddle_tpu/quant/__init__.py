"""paddle_tpu.quant — quantization-aware training and post-training
quantization.

Reference analog: `python/paddle/fluid/contrib/slim/quantization/`
(QuantizationTransformPass program rewriting for QAT, imperative QAT
`imperative/qat.py`, PostTrainingQuantization
`post_training_quantization.py`). TPU-native: no pass pipeline — QAT is a
layer substitution (Linear/Conv2D -> fake-quant wrappers with
straight-through estimators, all fused by XLA), PTQ is activation-range
calibration over sample data, and converted inference layers run real int8
matmuls on the MXU (int8 is 2x bf16 throughput on v5e+).
"""
from .qat import (FakeQuantAbsMax, QuantizedLinear, QuantizedConv2D,  # noqa: F401
                  QuantizedConv2DBN, QAT, quant_dequant,
                  quant_dequant_channelwise)
from .wo8 import (WeightOnlyInt8Linear, WeightOnlyInt8Embedding,  # noqa: F401
                  quantize_weights_int8, quantize_for_decode,
                  channelwise_int8)
from .ptq import (PTQ, AbsmaxQuantizer, HistQuantizer, KLQuantizer,  # noqa: F401
                  Int8Linear, Int8Conv2D, fold_conv_bn)
