"""Weight-only int8 — the LLM decode bandwidth lever.

Autoregressive decode is WEIGHT-bandwidth-bound (each generated token
re-reads every matmul weight; activations are tiny), so storing Linear
weights as int8 + per-output-channel scales halves the HBM bytes per
step while activations and accumulation stay bf16/f32 — unlike the
act+weight Int8Linear path (`ptq.py`), no activation calibration is
needed and there is no activation-quantization error.

Reference analog: `contrib/slim` weight-quantize utilities
(`post_training_quantization.py` weight_quantize path); the
serving-world name for this recipe is "weight-only int8" (W8A16).

Usage:
    model = GPTForPretraining(cfg)
    model.set_state_dict(...)                  # trained weights
    quantize_weights_int8(model)               # in-place Linear swap
    out, _ = model.generate(ids, max_new_tokens=...)
"""
import numpy as np
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor, apply

__all__ = ["WeightOnlyInt8Linear", "WeightOnlyInt8Embedding",
           "quantize_weights_int8", "quantize_for_decode",
           "channelwise_int8"]


def channelwise_int8(w, bits=8):
    """Per-OUTPUT-channel symmetric int8: returns (wq int8, scale f32)
    with w ~= wq * scale. Shared by the weight-only path here and the
    act+weight Int8Linear in ptq.py."""
    qmax = 2.0 ** (bits - 1) - 1
    scale = np.maximum(np.max(np.abs(w), axis=0), 1e-8) / qmax   # [out]
    wq = np.clip(np.round(w / scale), -qmax, qmax).astype(np.int8)
    return wq, scale.astype(np.float32)


class WeightOnlyInt8Linear(nn.Layer):
    """Drop-in Linear replacement: w int8 [in, out] + f32 scale [out];
    forward dequantizes IN VMEM after the 1-byte-per-weight HBM read
    (the cast + scale fuse into the matmul's epilogue under XLA).
    wq/w_scale are persistable BUFFERS so state_dict round-trips the
    quantized weights (save-after-quantize serving flow)."""

    def __init__(self, layer, bits=8):
        super().__init__()
        wq, ws = channelwise_int8(layer.weight.numpy(), bits)
        self.register_buffer("w_scale", Tensor(jnp.asarray(ws)),
                             persistable=True)
        self.register_buffer("wq", Tensor(jnp.asarray(wq)),
                             persistable=True)
        self.bias = layer.bias

    def forward(self, x):
        def fn(xv, wq, ws, *maybe_bias):
            # int8 -> activation dtype in VMEM; bf16 MXU matmul; scale
            # per out-channel in the epilogue
            out = jnp.matmul(xv, wq.astype(xv.dtype))
            out = out * ws.astype(xv.dtype)
            if maybe_bias:
                out = out + maybe_bias[0].astype(out.dtype)
            return out
        args = (x, self.wq, self.w_scale) + (
            (self.bias,) if self.bias is not None else ())
        return apply(fn, *args)


class WeightOnlyInt8Embedding(nn.Layer):
    """Embedding with int8 rows + per-ROW f32 scales. One quantization
    serves BOTH uses of a tied LM-head table: the lookup dequantizes the
    gathered rows, and the vocab projection's out-channels ARE the rows,
    so the head matmul reads the same int8 table and applies the scale
    in its epilogue (see GPTForPretraining.forward's quantized branch —
    scaling AFTER the contraction avoids materializing a dequantized
    [V, H] temp)."""

    @property
    def _HEAD_BLOCK(self):
        # single source of truth: the pad target IS the kernel block
        from ..ops.pallas_int8 import _BLOCK_V
        return _BLOCK_V

    def __init__(self, layer, bits=8):
        super().__init__()
        w = layer.weight.numpy()                     # [V, H]
        wq_t, ws = channelwise_int8(w.T, bits)       # per-ROW of w
        wq, V = wq_t.T, w.shape[0]
        # pad rows to the pallas head-kernel block once at quantize
        # time (scale 0 on pad rows; head consumers slice to true V)
        pad = (-V) % self._HEAD_BLOCK
        if pad:
            wq = np.concatenate(
                [wq, np.zeros((pad, w.shape[1]), np.int8)], axis=0)
            ws = np.concatenate([ws, np.zeros((pad,), np.float32)])
        self.num_embeddings = V
        self.register_buffer("wq", Tensor(jnp.asarray(wq)),
                             persistable=True)       # int8 [Vp, H]
        self.register_buffer("w_scale", Tensor(jnp.asarray(ws)),
                             persistable=True)       # f32 [Vp]
        self._padding_idx = getattr(layer, "_padding_idx", None)

    def forward(self, x):
        pad = self._padding_idx
        n_real = self.num_embeddings

        def fn(ids, wq, ws):
            # dequantize into the SCALE's dtype: generation's
            # _cast_params casts the float scale buffer to the decode
            # compute dtype (bf16), so the rows enter the stack in the
            # same dtype an unquantized embedding would — emitting f32
            # here would silently downgrade the whole bf16 decode
            # clip to the TRUE vocab (not the padded table): an
            # out-of-range id must keep mapping to the last real row,
            # not to a zero-scale pad row
            ids = jnp.clip(ids, 0, n_real - 1)
            rows = wq[ids].astype(ws.dtype) * ws[ids][..., None]
            if pad is not None:
                # F.embedding masks the padding row at LOOKUP time (the
                # stored row can drift); mirror it
                rows = jnp.where((ids == pad)[..., None],
                                 jnp.zeros((), rows.dtype), rows)
            return rows
        from ..core.tensor import apply as _apply
        from ..tensor._helpers import ensure_tensor
        return _apply(fn, ensure_tensor(x), self.wq, self.w_scale)


def _holds_wo8(layer):
    for child in layer._sub_layers.values():
        if isinstance(child, (WeightOnlyInt8Linear, WeightOnlyInt8Embedding)):
            return True
        if _holds_wo8(child):
            return True
    return False


def quantize_for_decode(model, bits=8, min_features=0):
    """THE weight-only-int8 entry for decode consumers — bench.py's
    `decode_wo8` phase and the serving engine's `weights="wo8"` mode
    share this one implementation (ISSUE 8 satellite: no bench-local
    quantization drift). Thin discipline over `quantize_weights_int8`:

    - idempotent: an already-quantized model is a no-op (returns 0),
      so an engine built over a pre-quantized checkpoint doesn't
      double-quantize (which would quantize the int8 *scales*);
    - loud: a model with NOTHING to quantize raises instead of
      silently serving fp weights under a "wo8" label.

    Returns the number of swapped layers."""
    if _holds_wo8(model):
        return 0
    swapped = quantize_weights_int8(model, bits=bits,
                                    min_features=min_features)
    if swapped == 0:
        raise ValueError(
            "quantize_for_decode: model holds no quantizable nn.Linear "
            "layers — refusing to serve full-precision weights as wo8")
    return swapped


def quantize_weights_int8(layer, bits=8, min_features=0,
                          embeddings=False):
    """Walk the layer tree replacing every nn.Linear with a
    WeightOnlyInt8Linear in place (norms are untouched). With
    embeddings=True, nn.Embedding tables are also quantized per-row —
    including a tied LM-head table, whose vocab projection then reads
    int8 (GPT's head path detects the quantized wte). NOTE measured on
    v5e (GPT-125M decode, bf16 11.8k tok/s, linears-only 15.9-18.8k):
    embeddings=True is SLOWER than bf16 for the head even through the
    dedicated pallas int8 matvec (11.1k; the XLA einsum materializes a
    dequantized [V, H] copy and is worse still at 10.8k) — at decode
    sizes the per-step kernel overhead eats the 39MB-vs-77MB read
    saving. Default False; memory-constrained serving may still want
    the ~2x smaller table, and the pallas head is its best-known path
    (ops/pallas_int8.py). min_features skips small
    projections whose bandwidth doesn't matter. Returns the count of
    swapped layers."""
    swapped = 0
    for name, child in list(layer._sub_layers.items()):
        if isinstance(child, nn.Linear):
            w = child.weight
            if min(w.shape) >= min_features:
                layer._sub_layers[name] = WeightOnlyInt8Linear(child, bits)
                swapped += 1
        elif embeddings and isinstance(child, nn.Embedding):
            if min(child.weight.shape) >= min_features:
                layer._sub_layers[name] = WeightOnlyInt8Embedding(child,
                                                                  bits)
                swapped += 1
        else:
            swapped += quantize_weights_int8(child, bits, min_features,
                                             embeddings)
    return swapped
