"""Reader decorators (reference `python/paddle/reader/decorator.py`):
functional combinators over no-arg sample-generator factories — the
pre-DataLoader composition layer older reference code uses."""
import itertools
import random as _random

__all__ = ["cache", "map_readers", "buffered", "compose", "chain",
           "shuffle", "firstn", "xmap_readers", "multiprocess_reader"]


def cache(reader):
    items = None

    def rd():
        nonlocal items
        if items is None:
            items = list(reader())
        return iter(items)
    return rd


def map_readers(func, *readers):
    def rd():
        for xs in zip(*[r() for r in readers]):
            yield func(*xs)
    return rd


def buffered(reader, size):
    """Prefetch up to `size` items on a feeder thread. The feeder polls
    a stop flag so an abandoned consumer (early break / GC'd generator)
    releases the thread and the source reader instead of leaking them
    blocked in q.put."""
    import queue
    import threading
    end = object()

    def rd():
        q = queue.Queue(maxsize=size)
        stop = threading.Event()

        def _put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def feed():
            try:
                for item in reader():
                    if not _put(item):
                        return
            finally:
                _put(end)
        threading.Thread(target=feed, daemon=True).start()
        try:
            while True:
                item = q.get()
                if item is end:
                    return
                yield item
        finally:
            stop.set()
    return rd


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def rd():
        iters = [r() for r in readers]
        zipper = zip(*iters) if check_alignment \
            else itertools.zip_longest(*iters)
        for xs in zipper:
            out = ()
            for x in xs:
                out = out + (x if isinstance(x, tuple) else (x,))
            yield out
    return rd


def chain(*readers):
    def rd():
        for r in readers:
            yield from r()
    return rd


def shuffle(reader, buf_size):
    def rd():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return rd


def firstn(reader, n):
    def rd():
        return itertools.islice(reader(), n)
    return rd


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Parallel map over a reader via threads (reference uses a thread
    pool too; the heavy multiprocess path is io.DataLoader)."""
    from concurrent.futures import ThreadPoolExecutor

    def rd():
        with ThreadPoolExecutor(process_num) as ex:
            it = reader()
            pending = []
            for item in it:
                pending.append(ex.submit(mapper, item))
                if len(pending) >= buffer_size:
                    yield pending.pop(0).result()
            for f in pending:
                yield f.result()
    return rd


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Degenerates to chain: single-controller JAX drives the chips from
    one process; real multiprocess loading lives in io.DataLoader's
    fork-safe spawn/forkserver workers (io.prefetch — os.fork() under
    multithreaded JAX deadlocks, so it is never used here either)."""
    return chain(*readers)
