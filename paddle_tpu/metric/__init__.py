"""paddle_tpu.metric — mirrors `python/paddle/metric/metrics.py`."""
import numpy as np

from ..core.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = np.asarray(pred) if not isinstance(pred, Tensor) else pred.numpy()
        label = np.asarray(label) if not isinstance(label, Tensor) else label.numpy()
        order = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        correct = (order == label[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        if isinstance(correct, Tensor):
            correct = correct.numpy()
        n = correct.reshape(-1, correct.shape[-1]).shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].sum()
            self.count[i] += n
        accs = self.total / np.maximum(self.count, 1)
        return accs[0] if len(self.topk) == 1 else accs

    def accumulate(self):
        accs = self.total / np.maximum(self.count, 1)
        return float(accs[0]) if len(self.topk) == 1 else [float(a) for a in accs]

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        pred_bin = (preds.reshape(-1) > 0.5).astype(np.int32)
        labels = labels.reshape(-1).astype(np.int32)
        self.tp += int(((pred_bin == 1) & (labels == 1)).sum())
        self.fp += int(((pred_bin == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        pred_bin = (preds.reshape(-1) > 0.5).astype(np.int32)
        labels = labels.reshape(-1).astype(np.int32)
        self.tp += int(((pred_bin == 1) & (labels == 1)).sum())
        self.fn += int(((pred_bin == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        if preds.ndim == 2:
            preds = preds[:, -1]
        labels = labels.reshape(-1)
        idx = np.minimum((preds * self.num_thresholds).astype(np.int64),
                         self.num_thresholds)
        for i, lab in zip(idx, labels):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds, descending
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    import jax.numpy as jnp
    from ..tensor._helpers import ensure_tensor
    input = ensure_tensor(input)  # noqa: A001
    label = ensure_tensor(label)
    iv, lv = input._value, label._value
    if lv.ndim == iv.ndim:
        lv = lv.reshape(lv.shape[:-1])
    import jax
    _, top_idx = jax.lax.top_k(iv, k)
    correct_mask = jnp.any(top_idx == lv[..., None], axis=-1)
    return Tensor(jnp.mean(correct_mask.astype(jnp.float32)))
