"""paddle_tpu.amp — mixed precision.

Parity: `python/paddle/amp/` (auto_cast `amp/auto_cast.py:21`, GradScaler
`grad_scaler.py:26`; reference kernels `operators/amp/
check_finite_and_unscale_op.cc`, `update_loss_scaling_op.cc`).

TPU-native stance: bf16 is the native fast dtype; it has fp32's exponent
range, so **loss scaling is unnecessary** for bf16 (GradScaler becomes a
near-no-op that still tracks the API). auto_cast('bfloat16') casts op inputs
at the eager-dispatch boundary (the analog of the tracer-level cast insertion
in `imperative/amp_auto_cast.cc`), and under jit the casts compile away into
bf16 MXU matmuls.
"""
import contextlib
import threading

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..core.dtype import convert_dtype, float32, bfloat16, float16

# default op lists (reference `fp16_lists.py` AutoMixedPrecisionLists):
# WHITE ops compute in the low precision (MXU-bound — the FLOPs live
# here); BLACK ops keep their NUMERICS-CRITICAL internal math in f32
# (softmax/norm statistics and reduction accumulators — consulted via
# amp_op_dtype by the op implementations). TPU-native deviation from the
# reference: black does NOT materialize f32 activation copies (conv nets
# are HBM-bound; reductions accumulate in f32 off low-precision inputs
# instead — same numerics safety, half the traffic). Everything else
# runs in its input dtype.
_DEFAULT_WHITE = frozenset({
    "matmul", "conv", "linear", "mul", "einsum", "attention", "bmm",
})
_DEFAULT_BLACK = frozenset({
    "softmax_with_cross_entropy", "cross_entropy", "layer_norm", "exp",
    "log", "mean", "sum", "cos_sim", "norm", "reduce_sum",
})


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = bfloat16
        self.level = "O1"
        self.white = _DEFAULT_WHITE
        self.black = _DEFAULT_BLACK


_state = _AmpState()


def amp_state():
    return _state


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = (_state.enabled, _state.dtype, _state.level, _state.white,
            _state.black)
    _state.enabled = enable
    _state.dtype = convert_dtype(dtype)
    _state.level = level
    # reference semantics (`fp16_lists.py`): custom white entries are
    # REMOVED from black and vice versa
    white = set(_DEFAULT_WHITE) | set(custom_white_list or ())
    black = set(_DEFAULT_BLACK) | set(custom_black_list or ())
    white -= set(custom_black_list or ())
    black -= set(custom_white_list or ())
    _state.white = frozenset(white)
    _state.black = frozenset(black)
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.white,
         _state.black) = prev


amp_guard = auto_cast


def white_black_list():
    """Active (white, black) op-name sets."""
    return _state.white, _state.black


def amp_op_dtype(op, input_dtype):
    """Accumulation/statistics dtype for `op`'s internal math: f32 when
    the op is black (the default for softmax/norm/reduction numerics),
    the amp compute dtype when the user white-lists it, the input dtype
    otherwise. Callers: layer_norm stats, cross-entropy log-sum-exp."""
    if not _state.enabled:
        return input_dtype
    if op in _state.black:
        return jnp.float32
    if op in _state.white:
        return _state.dtype
    return input_dtype


def maybe_cast_to_compute(x_value, op="matmul"):
    """Called by compute-bound functionals (linear/matmul/conv/einsum)
    when amp is enabled: white ops cast down to the amp dtype, black ops
    cast up to f32, everything else keeps its input dtype."""
    if not _state.enabled:
        return x_value
    if op in _state.black:
        return x_value.astype(jnp.float32) \
            if x_value.dtype != jnp.float32 else x_value
    if op in _state.white and x_value.dtype in (jnp.float32,):
        return x_value.astype(_state.dtype)
    return x_value


class GradScaler:
    """Dynamic loss scaling — needed for fp16, a no-op pass-through for bf16
    (kept for API parity; `init_loss_scaling=1` disables scaling)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, loss):
        if not self._enable:
            return loss
        s = self._scale
        return apply(lambda v: v * s, loss)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found_inf = False
        for p in optimizer._parameter_list or []:
            if p.grad is not None:
                g = p.grad._value * inv
                found_inf = found_inf | bool(
                    np.any(~np.isfinite(np.asarray(g))))
                p.grad._value = g
        self._found_inf = found_inf

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        pass

    def _update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


AmpScaler = GradScaler


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate analog: for O2, cast model params to the
    compute dtype; fp32 master copies are created by the optimizer's
    multi_precision path (on by default for Adam/AdamW/Momentum) the
    first time it sees a low-precision param, so updates accumulate at
    full precision."""
    dt = convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.astype(dt)
    if optimizers is None:
        return models
    return models, optimizers
