"""Vision transforms — parity with `python/paddle/vision/transforms/`.

Host-side numpy implementations (transforms run in DataLoader workers on
CPU; device work starts at the model). HWC uint8/float numpy in, numpy out;
ToTensor converts to CHW float Tensors.
"""
import numbers

import numpy as np

from ..core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def _pad_with_fill(img, widths, padding_mode, fill):
    """np.pad with reference fill semantics: a sequence fill is a
    PER-CHANNEL constant color (np.pad's own sequence rule is per-axis,
    which crashes or mis-fills for [left,top,right,bottom] layouts)."""
    if padding_mode != "constant":
        return np.pad(img, widths, mode=padding_mode)
    if np.isscalar(fill):
        return np.pad(img, widths, constant_values=fill)
    fill = np.asarray(fill)
    if img.ndim < 3 or fill.size != img.shape[-1]:
        raise ValueError(
            f"fill {fill.tolist()} must match the channel count "
            f"{img.shape[-1] if img.ndim >= 3 else 1}")
    chans = [np.pad(img[..., c], widths[:-1], constant_values=fill[c])
             for c in range(img.shape[-1])]
    return np.stack(chans, axis=-1)


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))


def _size_pair(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


def _resize_np(img, h, w, interpolation="bilinear"):
    ih, iw = img.shape[:2]
    if (ih, iw) == (h, w):
        return img
    # separable bilinear/nearest on numpy
    ys = np.linspace(0, ih - 1, h) if interpolation != "nearest" else \
        np.minimum((np.arange(h) * ih / h).astype(np.int64), ih - 1)
    xs = np.linspace(0, iw - 1, w) if interpolation != "nearest" else \
        np.minimum((np.arange(w) * iw / w).astype(np.int64), iw - 1)
    if interpolation == "nearest":
        return img[ys][:, xs]
    y0 = np.floor(ys).astype(np.int64)
    y1 = np.minimum(y0 + 1, ih - 1)
    x0 = np.floor(xs).astype(np.int64)
    x1 = np.minimum(x0 + 1, iw - 1)
    wy = (ys - y0)[:, None, None] if img.ndim == 3 else (ys - y0)[:, None]
    wx = (xs - x0)[None, :, None] if img.ndim == 3 else (xs - x0)[None, :]
    imgf = img.astype(np.float32)
    top = imgf[y0][:, x0] * (1 - wx) + imgf[y0][:, x1] * wx
    bot = imgf[y1][:, x0] * (1 - wx) + imgf[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(img.dtype) if np.issubdtype(img.dtype, np.floating) \
        else np.clip(out, 0, 255).astype(img.dtype)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        if isinstance(self.size, numbers.Number):
            ih, iw = img.shape[:2]
            scale = self.size / min(ih, iw)
            h, w = int(round(ih * scale)), int(round(iw * scale))
        else:
            h, w = _size_pair(self.size)
        return _resize_np(img, h, w, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = _size_pair(size)

    def _apply_image(self, img):
        h, w = self.size
        ih, iw = img.shape[:2]
        top = max((ih - h) // 2, 0)
        left = max((iw - w) // 2, 0)
        return img[top:top + h, left:left + w]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = _size_pair(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        if padding_mode not in ("constant", "edge", "reflect", "symmetric"):
            raise ValueError(f"unknown padding_mode {padding_mode!r}")
        self.padding_mode = padding_mode

    def _pad(self, img, pads):
        return _pad_with_fill(img, pads, self.padding_mode, self.fill)

    def _apply_image(self, img):
        h, w = self.size
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else [self.padding] * 4
            pads = [(p[1], p[3]), (p[0], p[2])] + \
                [(0, 0)] * (img.ndim - 2)
            img = self._pad(img, pads)
        ih, iw = img.shape[:2]
        if self.pad_if_needed and (ih < h or iw < w):
            ph, pw = max(h - ih, 0), max(w - iw, 0)
            pads = [(ph, ph), (pw, pw)] + [(0, 0)] * (img.ndim - 2)
            img = self._pad(img, pads)
            ih, iw = img.shape[:2]
        top = np.random.randint(0, max(ih - h, 0) + 1)
        left = np.random.randint(0, max(iw - w, 0) + 1)
        return img[top:top + h, left:left + w]


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = _size_pair(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        ih, iw = img.shape[:2]
        area = ih * iw
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= iw and 0 < h <= ih:
                top = np.random.randint(0, ih - h + 1)
                left = np.random.randint(0, iw - w + 1)
                crop = img[top:top + h, left:left + w]
                return _resize_np(crop, *self.size,
                                  interpolation=self.interpolation)
        return _resize_np(CenterCrop(min(ih, iw))._apply_image(img),
                          *self.size, interpolation=self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[::-1].copy()
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            mean = self.mean.reshape(-1, 1, 1)
            std = self.std.reshape(-1, 1, 1)
        else:
            mean, std = self.mean, self.std
        return (img - mean) / std


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        if np.issubdtype(img.dtype, np.integer):
            img = img.astype(np.float32) / 255.0
        if self.data_format == "CHW":
            img = img.transpose(2, 0, 1)
        return img.astype(np.float32)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(img.astype(np.float32) * f, 0,
                       255 if np.issubdtype(img.dtype, np.integer) else None
                       ).astype(img.dtype)


def _clip_like(img, ref):
    hi = 255 if np.issubdtype(ref.dtype, np.integer) else None
    return np.clip(img, 0, hi).astype(ref.dtype)


def _gray(img):
    """Luminance of an HWC image (channels-last); grayscale passthrough."""
    if img.ndim == 2 or img.shape[-1] == 1:
        return img.astype(np.float32)
    return (img[..., :3].astype(np.float32) @
            np.asarray([0.299, 0.587, 0.114], np.float32))[..., None]


def _blend_rgb(img, fn):
    """Apply fn to the RGB channels only, passing alpha/extras through."""
    if img.ndim == 2 or img.shape[-1] <= 3:
        return _clip_like(fn(img.astype(np.float32)), img)
    out = fn(img[..., :3].astype(np.float32))
    out = np.concatenate([out, img[..., 3:].astype(np.float32)], axis=-1)
    return _clip_like(out, img)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = _gray(img).mean()
        return _blend_rgb(img, lambda rgb: mean + (rgb - mean) * f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0 or img.ndim == 2 or img.shape[-1] == 1:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = _gray(img)
        return _blend_rgb(img, lambda rgb: gray + (rgb - gray) * f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0 or img.ndim == 2 or img.shape[-1] == 1:
            return img
        return _hue_shift(img, np.random.uniform(-self.value, self.value))


def _hue_shift(img, shift):
    scale = 255.0 if np.issubdtype(img.dtype, np.integer) else 1.0
    rgb = img[..., :3].astype(np.float32) / scale
    maxc = rgb.max(-1)
    minc = rgb.min(-1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-12), 0.0)
    dsafe = np.maximum(d, 1e-12)
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    h = np.where(maxc == r, (g - b) / dsafe % 6,
                 np.where(maxc == g, (b - r) / dsafe + 2,
                          (r - g) / dsafe + 4)) / 6.0
    h = np.where(d == 0, 0.0, h)
    h = (h + shift) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    out = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q]),
    ], axis=-1) * scale
    if img.shape[-1] > 3:  # preserve alpha/extra channels
        out = np.concatenate(
            [out, img[..., 3:].astype(np.float32)], axis=-1)
    return _clip_like(out, img)


class ColorJitter(BaseTransform):
    """Randomly-ordered brightness/contrast/saturation/hue jitter
    (reference `python/paddle/vision/transforms/transforms.py` ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self._transforms = [
            BrightnessTransform(brightness),
            ContrastTransform(contrast),
            SaturationTransform(saturation),
            HueTransform(hue),
        ]

    def _apply_image(self, img):
        for i in np.random.permutation(len(self._transforms)):
            img = self._transforms[i]._apply_image(img)
        return img


def to_tensor(pic, data_format="CHW"):
    return Tensor(ToTensor(data_format)._apply_image(np.asarray(pic)))


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)._apply_image(np.asarray(img))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)._apply_image(np.asarray(img))


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


# ---- round-3 parity: crop/pad/rotate/grayscale + functional forms ------
# (reference `python/paddle/vision/transforms/functional.py`)

def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width].copy()


def center_crop(img, output_size):
    img = np.asarray(img)
    th, tw = _size_pair(output_size)
    h, w = img.shape[:2]
    return crop(img, max(0, (h - th) // 2), max(0, (w - tw) // 2), th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    """padding: int | [pad_lr, pad_tb] | [left, top, right, bottom]."""
    img = np.asarray(img)
    if isinstance(padding, int):
        l = t = r = b = padding
    elif len(padding) == 2:
        l, t = padding
        r, b = padding
    else:
        l, t, r, b = padding
    widths = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    return _pad_with_fill(img, widths, mode, fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate counter-clockwise by `angle` degrees about `center` (image
    center by default). Inverse-map + gather — no scipy dependency."""
    img = np.asarray(img)
    h, w = img.shape[:2]
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    if expand:
        nh = int(np.ceil(abs(h * cos) + abs(w * sin)))
        nw = int(np.ceil(abs(w * cos) + abs(h * sin)))
    else:
        nh, nw = h, w
    yy, xx = np.meshgrid(np.arange(nh), np.arange(nw), indexing="ij")
    oy, ox = (nh - 1) / 2.0, (nw - 1) / 2.0
    # rotate output coords BACK into source space
    sy = cy + (yy - oy) * cos - (xx - ox) * sin
    sx = cx + (yy - oy) * sin + (xx - ox) * cos
    if interpolation == "bilinear":
        y0 = np.floor(sy).astype(np.int64)
        x0 = np.floor(sx).astype(np.int64)
        wy, wx = sy - y0, sx - x0
        out = 0.0
        for dy, fy in ((0, 1 - wy), (1, wy)):
            for dx, fx in ((0, 1 - wx), (1, wx)):
                yi = np.clip(y0 + dy, 0, h - 1)
                xi = np.clip(x0 + dx, 0, w - 1)
                contrib = img[yi, xi].astype(np.float32)
                f = (fy * fx)
                out = out + contrib * (f[..., None] if img.ndim == 3
                                       else f)
        out = out
    else:
        yi = np.clip(np.round(sy).astype(np.int64), 0, h - 1)
        xi = np.clip(np.round(sx).astype(np.int64), 0, w - 1)
        out = img[yi, xi].astype(np.float32)
    inside = (sy >= -0.5) & (sy <= h - 0.5) & (sx >= -0.5) & (sx <= w - 0.5)
    if img.ndim == 3:
        inside = inside[..., None]
    out = np.where(inside, out, np.float32(fill))
    return _clip_like(out, img)


def to_grayscale(img, num_output_channels=1):
    img = np.asarray(img)
    g = _gray(img)
    if g.ndim == 2:
        g = g[..., None]            # 2-D grayscale input: add channel axis
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=-1)
    return _clip_like(g, img)


def adjust_brightness(img, brightness_factor):
    img = np.asarray(img)
    return _clip_like(img.astype(np.float32) * brightness_factor, img)


def adjust_contrast(img, contrast_factor):
    img = np.asarray(img)
    mean = _gray(img).mean()
    return _blend_rgb(img, lambda rgb: mean + (rgb - mean) * contrast_factor)


def adjust_hue(img, hue_factor):
    img = np.asarray(img)
    if img.ndim == 2 or img.shape[-1] == 1:
        return img
    return _hue_shift(img, hue_factor)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding, self.fill = padding, fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand, self.center, self.fill = expand, center, fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)
