"""Model zoo — parity with `python/paddle/vision/models/__init__.py`."""
from .lenet import LeNet, lenet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock, resnet18, resnet34, resnet50,
    resnet101, resnet152, resnext50_32x4d, wide_resnet50_2,
)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2  # noqa: F401
