"""LeNet — parity with `python/paddle/vision/models/lenet.py` (capability
config 1: MNIST smoke)."""
from ...nn import (Conv2D, Linear, MaxPool2D, ReLU, Sequential, Layer)
from ...tensor.manipulation import flatten


class LeNet(Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(),
            MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = Sequential(
                Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, inputs):
        x = self.features(inputs)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def lenet(pretrained=False, num_classes=10):
    """LeNet factory with optional packaged fixture weights
    (`lenet_synthdigits`: self-trained on the synthetic digit task the
    suite's accuracy gates use)."""
    model = LeNet(num_classes=num_classes)
    if pretrained:
        from ...pretrained import load_pretrained
        load_pretrained(model, "lenet_synthdigits", pretrained)
    return model
