"""paddle_tpu.vision — mirrors `python/paddle/vision/`."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from . import detection  # noqa: F401

# ---- image backend + loading (reference `vision/image.py`) -----------
_IMAGE_BACKEND = "pil"


def set_image_backend(backend):
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r}")
    global _IMAGE_BACKEND
    _IMAGE_BACKEND = backend


def get_image_backend():
    return _IMAGE_BACKEND


def image_load(path, backend=None):
    """Load an image file. pil backend returns a PIL.Image (reference
    behavior); 'tensor'/'cv2' return HWC numpy (BGR for cv2 parity)."""
    backend = backend or _IMAGE_BACKEND
    from PIL import Image
    img = Image.open(path)
    if backend == "pil":
        return img
    import numpy as _np
    arr = _np.asarray(img)
    if backend == "cv2" and arr.ndim == 3 and arr.shape[-1] >= 3:
        arr = arr[..., [2, 1, 0]]       # RGB -> BGR, cv2 convention
    return arr
