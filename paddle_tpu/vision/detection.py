"""Detection post-processing / target-generation op family.

Parity target: `python/paddle/fluid/layers/detection.py` and the kernels
in `paddle/fluid/operators/detection/` (multiclass_nms, matrix_nms,
prior_box, density_prior_box, anchor_generator, box_coder, box_clip,
iou_similarity, bipartite_match, generate_proposals,
distribute_fpn_proposals). TPU-first redesign of the reference's
LoD-everywhere contract: every op here returns FIXED-SHAPE padded arrays
plus a valid count (or -1 labels) instead of variable-length LoD
tensors, so entire detection heads jit into one XLA program. Greedy NMS
keeps its sequential semantics as a `lax.fori_loop` of vectorized mask
updates; matrix_nms is embarrassingly parallel and is the preferred
TPU path.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..tensor._helpers import ensure_tensor
from ._boxes import iou_matrix, nms_mask, NEG_INF

__all__ = [
    "iou_similarity", "box_coder", "box_clip", "bipartite_match",
    "multiclass_nms", "matrix_nms", "prior_box", "density_prior_box",
    "anchor_generator", "generate_proposals", "distribute_fpn_proposals",
]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU, [N,4] x [M,4] -> [N,M]
    (`fluid/layers/detection.py:765`, `iou_similarity_op.h`)."""
    return Tensor(iou_matrix(_val(ensure_tensor(x)).astype(jnp.float32),
                             _val(ensure_tensor(y)).astype(jnp.float32),
                             box_normalized))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors
    (`fluid/layers/detection.py:819`, `box_coder_op.h`).

    encode: target [N,4] vs priors [M,4] -> [N,M,4] deltas.
    decode: deltas [N,M,4] (or [N,4] broadcast along `axis`) -> boxes.
    prior_box_var: None | [M,4] Tensor | 4-list.
    """
    pb = _val(ensure_tensor(prior_box)).astype(jnp.float32)
    tb = _val(ensure_tensor(target_box)).astype(jnp.float32)
    if prior_box_var is None:
        var = jnp.ones((1, 4), jnp.float32)
    elif isinstance(prior_box_var, (list, tuple)):
        var = jnp.asarray(prior_box_var, jnp.float32).reshape(1, 4)
    else:
        var = _val(ensure_tensor(prior_box_var)).astype(jnp.float32)

    off = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + off
    ph = pb[:, 3] - pb[:, 1] + off
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5

    if code_type.lower() == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + off
        th = tb[:, 3] - tb[:, 1] + off
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None]) / pw[None]
        dy = (tcy[:, None] - pcy[None]) / ph[None]
        dw = jnp.log(jnp.maximum(tw[:, None] / pw[None], 1e-10))
        dh = jnp.log(jnp.maximum(th[:, None] / ph[None], 1e-10))
        out = jnp.stack([dx, dy, dw, dh], -1) / var[None]
        return Tensor(out)

    # decode: tb is [N, M, 4] deltas (or [N, 4] against priors along axis)
    if tb.ndim == 2:
        tb = tb[:, None, :] if axis == 0 else tb[None, :, :]
    if axis == 0:
        pcx_, pcy_, pw_, ph_, var_ = (pcx[None, :], pcy[None, :],
                                      pw[None, :], ph[None, :], var[None])
    else:
        pcx_, pcy_, pw_, ph_, var_ = (pcx[:, None], pcy[:, None],
                                      pw[:, None], ph[:, None],
                                      var[:, None] if var.shape[0] > 1
                                      else var[None])
    d = tb * var_
    cx = d[..., 0] * pw_ + pcx_
    cy = d[..., 1] * ph_ + pcy_
    w = jnp.exp(d[..., 2]) * pw_
    h = jnp.exp(d[..., 3]) * ph_
    out = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                     cx + w * 0.5 - off, cy + h * 0.5 - off], -1)
    return Tensor(out)


def box_clip(input, im_info, name=None):
    """Clip boxes to image extents (`fluid/layers/detection.py:3050`).
    im_info per image: (height, width, scale) — boxes clipped to
    [0, dim/scale - 1]."""
    b = _val(ensure_tensor(input)).astype(jnp.float32)
    info = _val(ensure_tensor(im_info)).astype(jnp.float32)
    if info.ndim == 1:
        info = info[None]
    hmax = info[:, 0] / info[:, 2] - 1
    wmax = info[:, 1] / info[:, 2] - 1
    while hmax.ndim < b.ndim - 1:
        hmax, wmax = hmax[..., None], wmax[..., None]
    x1 = jnp.clip(b[..., 0], 0, wmax)
    y1 = jnp.clip(b[..., 1], 0, hmax)
    x2 = jnp.clip(b[..., 2], 0, wmax)
    y2 = jnp.clip(b[..., 3], 0, hmax)
    return Tensor(jnp.stack([x1, y1, x2, y2], -1))


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=None, name=None):
    """Greedy bipartite matching (`fluid/layers/detection.py:1324`,
    `bipartite_match_op.cc`): repeatedly take the global argmax of the
    [R, C] distance matrix, pair that row/col, mask both out. The
    reference's data-dependent loop becomes a `lax.scan` of min(R, C)
    fully vectorized steps. Returns (match_indices [C] int32 — row
    matched to each column, -1 if none; match_dist [C]).
    'per_prediction' additionally matches every unmatched column to its
    argmax row when that distance > dist_threshold."""
    d = _val(ensure_tensor(dist_matrix)).astype(jnp.float32)
    R, C = d.shape

    def step(carry, _):
        m, midx, mdist = carry
        flat = jnp.argmax(m)
        r, c = flat // C, flat % C
        best = m[r, c]
        take = best > 0
        midx = jnp.where(take, midx.at[c].set(r.astype(jnp.int32)), midx)
        mdist = jnp.where(take, mdist.at[c].set(best), mdist)
        m = jnp.where(take, m.at[r, :].set(NEG_INF).at[:, c].set(NEG_INF),
                      m)
        return (m, midx, mdist), None

    init = (d, jnp.full((C,), -1, jnp.int32), jnp.zeros((C,), jnp.float32))
    (_, midx, mdist), _ = jax.lax.scan(step, init, None,
                                       length=min(R, C))
    if match_type == "per_prediction":
        thr = 0.5 if dist_threshold is None else float(dist_threshold)
        col_best = d.argmax(0).astype(jnp.int32)
        col_dist = d.max(0)
        extra = (midx < 0) & (col_dist > thr)
        midx = jnp.where(extra, col_best, midx)
        mdist = jnp.where(extra, col_dist, mdist)
    return Tensor(midx), Tensor(mdist)


def _per_class_nms_pad(boxes, scores, score_threshold, nms_top_k,
                       nms_threshold, normalized, eta):
    """One class: mask sub-threshold, take top nms_top_k, greedy NMS.
    Returns (cand_boxes [K,4], cand_scores [K] with suppressed = NEG_INF,
    cand_idx [K] original box indices)."""
    s = jnp.where(scores > score_threshold, scores, NEG_INF)
    k = min(nms_top_k if nms_top_k > 0 else s.shape[0], s.shape[0])
    top_s, idx = jax.lax.top_k(s, k)
    b = boxes[idx]
    keep, order = nms_mask(b, top_s, nms_threshold, normalized, eta,
                           valid=top_s > NEG_INF / 2)
    sel = jnp.where(keep, top_s, NEG_INF)
    return b, sel, idx.astype(jnp.int32)


def _assemble_detections(flat_s, flat_b, flat_l, flat_i, ktk):
    """Shared final stage of multiclass/matrix NMS: global top-k over all
    per-class candidates -> ([ktk, 6] (label, score, box) padded with
    label = -1, valid count, [ktk] original box indices padded -1)."""
    kk = min(ktk, flat_s.shape[0])
    top_s, top_i = jax.lax.top_k(flat_s, kk)
    ok = top_s > NEG_INF / 2
    det = jnp.concatenate(
        [jnp.where(ok, flat_l[top_i], -1).astype(jnp.float32)[:, None],
         jnp.where(ok, top_s, 0.0)[:, None],
         jnp.where(ok[:, None], flat_b[top_i], 0.0)], -1)
    idx = jnp.where(ok, flat_i[top_i], -1)
    if kk < ktk:
        det = jnp.concatenate(
            [det, jnp.zeros((ktk - kk, 6), jnp.float32).at[:, 0].set(-1)],
            0)
        idx = jnp.concatenate([idx, jnp.full((ktk - kk,), -1, jnp.int32)],
                              0)
    return det, jnp.sum(ok.astype(jnp.int32)), idx


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None, return_index=False):
    """Multi-class NMS (`fluid/layers/detection.py:3269`,
    `multiclass_nms_op.cc`).

    bboxes [N, M, 4] (boxes shared across classes); scores [N, C, M].
    FIXED-SHAPE output (replaces the reference's LoD): detections
    [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2) padded with
    label = -1, plus nums [N] valid counts. With return_index=True, also
    the original box index [N, keep_top_k] (padded -1) between det and
    nums, matching the reference's Index output.
    """
    bv = _val(ensure_tensor(bboxes)).astype(jnp.float32)
    sv = _val(ensure_tensor(scores)).astype(jnp.float32)
    N, C, M = sv.shape
    ktk = keep_top_k if keep_top_k > 0 else C * M

    def per_image(b, s):
        def per_class(sc):
            return _per_class_nms_pad(b, sc, score_threshold, nms_top_k,
                                      nms_threshold, normalized, nms_eta)
        cb, cs, ci = jax.vmap(per_class)(s)       # [C, K, 4], [C, K] x2
        labels = jnp.broadcast_to(jnp.arange(C)[:, None], cs.shape)
        if 0 <= background_label < C:
            cs = jnp.where(labels == background_label, NEG_INF, cs)
        return _assemble_detections(cs.reshape(-1), cb.reshape(-1, 4),
                                    labels.reshape(-1), ci.reshape(-1),
                                    ktk)

    det, nums, idx = jax.vmap(per_image)(bv, sv)
    if return_index:
        return Tensor(det), Tensor(idx), Tensor(nums)
    return Tensor(det), Tensor(nums)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (`fluid/layers/detection.py:3553`, `matrix_nms_op.cc`;
    SOLOv2). Unlike greedy NMS this is one batched matrix computation —
    the natural TPU formulation: decay_i = min_j (f(iou_ij) /
    f(compensate_j)) over higher-scored j, f gaussian or linear.

    Same fixed-shape output contract as multiclass_nms.
    """
    bv = _val(ensure_tensor(bboxes)).astype(jnp.float32)
    sv = _val(ensure_tensor(scores)).astype(jnp.float32)
    N, C, M = sv.shape
    ktk = keep_top_k if keep_top_k > 0 else C * M

    def decay_fn(iou, compensate):
        if use_gaussian:
            return jnp.exp((compensate ** 2 - iou ** 2) / gaussian_sigma)
        return (1.0 - iou) / jnp.maximum(1.0 - compensate, 1e-10)

    def per_class(b, sc):
        s = jnp.where(sc > score_threshold, sc, NEG_INF)
        k = min(nms_top_k if nms_top_k > 0 else M, M)
        top_s, idx = jax.lax.top_k(s, k)
        sb = b[idx]
        valid = top_s > NEG_INF / 2
        iou = iou_matrix(sb, sb, normalized)
        upper = jnp.triu(jnp.ones((k, k), bool), 1)  # j < i pairs (row j)
        iou_hi = jnp.where(upper & valid[:, None] & valid[None, :],
                           iou, 0.0)                 # iou_hi[j, i], j<i
        compensate = jnp.max(iou_hi, 0)              # per j: max vs higher
        decay = jnp.where(upper, decay_fn(iou_hi, compensate[:, None]),
                          jnp.inf)
        decay = jnp.clip(jnp.min(decay, 0), 0.0, 1.0)
        new_s = jnp.where(valid, top_s * decay, NEG_INF)
        new_s = jnp.where(new_s > post_threshold, new_s, NEG_INF)
        return sb, new_s, idx.astype(jnp.int32)

    def per_image(b, s):
        cb, cs, ci = jax.vmap(lambda sc: per_class(b, sc))(s)
        labels = jnp.broadcast_to(jnp.arange(C)[:, None], cs.shape)
        if 0 <= background_label < C:
            cs = jnp.where(labels == background_label, NEG_INF, cs)
        return _assemble_detections(cs.reshape(-1), cb.reshape(-1, 4),
                                    labels.reshape(-1), ci.reshape(-1),
                                    ktk)

    det, nums, idx = jax.vmap(per_image)(bv, sv)
    outs = [Tensor(det)]
    if return_index:
        outs.append(Tensor(idx))
    if return_rois_num:
        outs.append(Tensor(nums))
    return tuple(outs) if len(outs) > 1 else outs[0]


# ---------------------------------------------------------------------------
# anchor generation
# ---------------------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (`fluid/layers/detection.py:1771`,
    `prior_box_op.h`). input [N,C,H,W] feature, image [N,C,Hi,Wi].
    Returns (boxes [H,W,P,4] normalized xyxy, variances [H,W,P,4])."""
    fh, fw = _val(ensure_tensor(input)).shape[-2:]
    ih, iw = _val(ensure_tensor(image)).shape[-2:]
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] \
        if max_sizes else []
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    step_w = float(steps[0]) or iw / fw
    step_h = float(steps[1]) or ih / fh

    whs = []
    for k, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                bs = np.sqrt(ms * max_sizes[k])
                whs.append((bs, bs))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                bs = np.sqrt(ms * max_sizes[k])
                whs.append((bs, bs))
    wh = jnp.asarray(whs, jnp.float32)                  # [P, 2]
    P = wh.shape[0]

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, P))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, P))
    bw = wh[None, None, :, 0] / 2
    bh = wh[None, None, :, 1] / 2
    boxes = jnp.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                       (cxg + bw) / iw, (cyg + bh) / ih], -1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           (fh, fw, P, 4))
    return Tensor(boxes), Tensor(var)


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    """Density prior boxes (`fluid/layers/detection.py:1932`,
    `density_prior_box_op.h`): each fixed_size spawns a density x density
    sub-grid of shifted anchors per ratio."""
    fh, fw = _val(ensure_tensor(input)).shape[-2:]
    ih, iw = _val(ensure_tensor(image)).shape[-2:]
    step_w = float(steps[0]) or iw / fw
    step_h = float(steps[1]) or ih / fh

    entries = []  # (w, h, shift_x_frac, shift_y_frac)
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            shift = 1.0 / density
            for di in range(density):
                for dj in range(density):
                    entries.append(
                        (bw, bh,
                         (dj + 0.5) * shift - 0.5,
                         (di + 0.5) * shift - 0.5))
    e = jnp.asarray(entries, jnp.float32)               # [P, 4]
    P = e.shape[0]
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cxg = cx[None, :, None] + e[None, None, :, 2] * step_w
    cyg = cy[:, None, None] + e[None, None, :, 3] * step_h
    bw = e[None, None, :, 0] / 2
    bh = e[None, None, :, 1] / 2
    cxg = jnp.broadcast_to(cxg, (fh, fw, P))
    cyg = jnp.broadcast_to(cyg, (fh, fw, P))
    boxes = jnp.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                       (cxg + bw) / iw, (cyg + bh) / ih], -1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           (fh, fw, P, 4))
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return Tensor(boxes), Tensor(var)


def anchor_generator(input, anchor_sizes, aspect_ratios, variance,
                     stride, offset=0.5, name=None):
    """RPN anchors (`fluid/layers/detection.py:2406`,
    `anchor_generator_op.h`): per feature-map cell, one anchor per
    (size, ratio) in INPUT-IMAGE pixel coords. Returns
    (anchors [H,W,A,4], variances [H,W,A,4])."""
    fh, fw = _val(ensure_tensor(input)).shape[-2:]
    sw, sh = float(stride[0]), float(stride[1])
    whs = []
    for r in aspect_ratios:
        base_w = round(np.sqrt(sw * sh / r))
        base_h = round(base_w * r)
        for s in anchor_sizes:
            whs.append((s / sw * base_w, s / sh * base_h))
    wh = jnp.asarray(whs, jnp.float32)
    A = wh.shape[0]
    cx = jnp.arange(fw, dtype=jnp.float32) * sw + offset * (sw - 1)
    cy = jnp.arange(fh, dtype=jnp.float32) * sh + offset * (sh - 1)
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, A))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, A))
    bw = (wh[None, None, :, 0] - 1) / 2
    bh = (wh[None, None, :, 1] - 1) / 2
    anchors = jnp.stack([cxg - bw, cyg - bh, cxg + bw, cyg + bh], -1)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           (fh, fw, A, 4))
    return Tensor(anchors), Tensor(var)


# ---------------------------------------------------------------------------
# proposal generation / FPN distribution
# ---------------------------------------------------------------------------

def generate_proposals(scores, bbox_deltas, im_shape, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=True, name=None):
    """RPN proposal generation (`fluid/layers/detection.py:2901`,
    `generate_proposals_v2_op.cc`): decode anchors with deltas, clip to
    the image, drop boxes smaller than min_size (masked, not compacted),
    take pre_nms_top_n by score, greedy-NMS, keep post_nms_top_n.

    scores [N, A, H, W]; bbox_deltas [N, 4A, H, W]; im_shape [N, 2]
    (h, w); anchors [H, W, A, 4]; variances [H, W, A, 4].
    Returns (rois [N, post_nms_top_n, 4], roi_probs [N, post_nms_top_n, 1],
    rois_num [N]) — fixed shapes, padded with zeros.
    """
    sv = _val(ensure_tensor(scores)).astype(jnp.float32)
    dv = _val(ensure_tensor(bbox_deltas)).astype(jnp.float32)
    imv = _val(ensure_tensor(im_shape)).astype(jnp.float32)
    av = _val(ensure_tensor(anchors)).astype(jnp.float32).reshape(-1, 4)
    vv = _val(ensure_tensor(variances)).astype(jnp.float32).reshape(-1, 4)
    N, A, H, W = sv.shape

    def per_image(s, d, im):
        # to anchor-major [H*W*A] ordering to match anchors.reshape
        s = s.transpose(1, 2, 0).reshape(-1)             # [H*W*A]
        d = d.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = av[:, 2] - av[:, 0] + 1.0
        ah = av[:, 3] - av[:, 1] + 1.0
        acx = av[:, 0] + aw * 0.5
        acy = av[:, 1] + ah * 0.5
        dd = d * vv
        cx = dd[:, 0] * aw + acx
        cy = dd[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(dd[:, 2], 10.0)) * aw
        h = jnp.exp(jnp.minimum(dd[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                           cx + w * 0.5 - 1, cy + h * 0.5 - 1], -1)
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, im[1] - 1),
                           jnp.clip(boxes[:, 1], 0, im[0] - 1),
                           jnp.clip(boxes[:, 2], 0, im[1] - 1),
                           jnp.clip(boxes[:, 3], 0, im[0] - 1)], -1)
        bw = boxes[:, 2] - boxes[:, 0] + 1
        bh = boxes[:, 3] - boxes[:, 1] + 1
        ok = (bw >= min_size) & (bh >= min_size)
        s = jnp.where(ok, s, NEG_INF)
        k = min(pre_nms_top_n, s.shape[0])
        top_s, idx = jax.lax.top_k(s, k)
        b = boxes[idx]
        keep, order = nms_mask(b, top_s, nms_thresh, normalized=False,
                               eta=eta, valid=top_s > NEG_INF / 2)
        kept_sorted = keep[order]
        rank = jnp.cumsum(kept_sorted.astype(jnp.int32)) - 1
        put = jnp.where(kept_sorted & (rank < post_nms_top_n), rank,
                        post_nms_top_n)
        rois = jnp.zeros((post_nms_top_n, 4), jnp.float32)
        rois = rois.at[put].set(b[order], mode="drop")
        probs = jnp.zeros((post_nms_top_n,), jnp.float32)
        probs = probs.at[put].set(top_s[order], mode="drop")
        n_val = jnp.minimum(kept_sorted.sum().astype(jnp.int32),
                            post_nms_top_n)
        return rois, probs[:, None], n_val

    rois, probs, nums = jax.vmap(per_image)(sv, dv, imv)
    if return_rois_num:
        return Tensor(rois), Tensor(probs), Tensor(nums)
    return Tensor(rois), Tensor(probs)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Route rois to FPN levels (`fluid/layers/detection.py:3680`,
    `distribute_fpn_proposals_op.cc`):
    level = floor(refer_level + log2(sqrt(area) / refer_scale)), clipped.

    fpn_rois [R, 4]. Fixed-shape contract: every level gets an [R, 4]
    array + a bool mask (invalid rows zeroed) instead of compacted LoD
    outputs; restore_ind is the identity permutation split by mask rank.
    Returns (multi_rois list, masks list, restore_ind [R]); with
    rois_num [n_images] given, additionally a list of per-level
    [n_images] counts (the reference's RoisNum outputs).
    """
    r = _val(ensure_tensor(fpn_rois)).astype(jnp.float32)
    off = 1.0 if pixel_offset else 0.0
    area = (r[:, 2] - r[:, 0] + off) * (r[:, 3] - r[:, 1] + off)
    scale = jnp.sqrt(jnp.maximum(area, 1e-10))
    lvl = jnp.floor(refer_level + jnp.log2(scale / refer_scale + 1e-10))
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)

    multi_rois, masks = [], []
    for level in range(min_level, max_level + 1):
        m = lvl == level
        multi_rois.append(Tensor(jnp.where(m[:, None], r, 0.0)))
        masks.append(Tensor(m))
    # original position of each roi in level-major order
    order = jnp.argsort(lvl * r.shape[0] + jnp.arange(r.shape[0]))
    restore = jnp.zeros((r.shape[0],), jnp.int32).at[order].set(
        jnp.arange(r.shape[0], dtype=jnp.int32))
    if rois_num is None:
        return multi_rois, masks, Tensor(restore)
    nv = _val(ensure_tensor(rois_num)).astype(jnp.int32)
    bidx = jnp.repeat(jnp.arange(nv.shape[0]), nv,
                      total_repeat_length=r.shape[0])
    per_level_nums = []
    for level in range(min_level, max_level + 1):
        m = (lvl == level)
        per_level_nums.append(Tensor(jnp.sum(
            m[None, :] & (bidx[None, :] == jnp.arange(nv.shape[0])[:, None]),
            axis=1).astype(jnp.int32)))
    return multi_rois, masks, Tensor(restore), per_level_nums
