"""Vision/detection operators — `paddle.vision.ops` parity.

Parity targets: `python/paddle/vision/ops.py` (roi_align/roi_pool/
psroi_pool/deform_conv2d/yolo_box/yolo_loss + Layer wrappers) and the
kernels behind them in `paddle/fluid/operators/detection/` (18.7k LoC of
CUDA/C++). TPU-first redesign rather than translation:

- Everything is fixed-shape: rois are dense `[R, 4]` with a `boxes_num`
  split (no LoD), NMS-style ops return padded arrays + valid counts.
- The per-ROI pixel loops of the CUDA kernels become broadcasted
  gather/one-hot-mask reductions that XLA tiles onto the VPU/MXU;
  bilinear sampling is 4 gathers + a weighted sum, so its VJP is the
  scatter-add the reference hand-writes in `roi_align_op.cu` backward.
- Differentiable ops route through `core.tensor.apply`, so the eager
  tape and jit tracing both see them as one op with a jax.vjp.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..tensor._helpers import ensure_tensor
from ..nn.layer.layers import Layer
from ._boxes import iou_matrix, nms_mask, NEG_INF

__all__ = [
    "roi_align", "RoIAlign", "roi_pool", "RoIPool", "psroi_pool",
    "PSRoIPool", "deform_conv2d", "DeformConv2D", "yolo_box", "yolo_loss",
    "read_file", "decode_jpeg",
    "nms",
]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


# ---------------------------------------------------------------------------
# bilinear sampling (shared by roi_align / deform_conv2d)
# ---------------------------------------------------------------------------

def _bilinear(feat, y, x):
    """feat [C,H,W]; y,x [...] float feature coords -> [C, ...].

    roi_align convention (reference `roi_align_op.cu` BilinearInterpolate):
    points more than one pixel outside the map are 0; coords are clipped
    into [0, dim-1] before the 4-corner weighted sum.
    """
    H, W = feat.shape[-2:]
    outside = (y < -1.0) | (y > H) | (x < -1.0) | (x > W)
    y = jnp.clip(y, 0.0, H - 1)
    x = jnp.clip(x, 0.0, W - 1)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly, lx = y - y0, x - x0
    v00 = feat[:, y0, x0]
    v01 = feat[:, y0, x1]
    v10 = feat[:, y1, x0]
    v11 = feat[:, y1, x1]
    val = ((1 - ly) * (1 - lx) * v00 + (1 - ly) * lx * v01
           + ly * (1 - lx) * v10 + ly * lx * v11)
    return jnp.where(outside, 0.0, val)


def _bilinear_zero(feat, y, x):
    """feat [C,H,W]; y,x [...] float coords -> [C, ...] with ZERO padding:
    each of the 4 corners contributes only if it lies inside the map
    (deformable-conv convention, `deformable_conv_op.cu` DmcnIm2colBilinear
    — distinct from roi_align's clamp-into-map rule in `_bilinear`)."""
    H, W = feat.shape[-2:]
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    ly, lx = y - y0, x - x0

    def corner(yi, xi, w):
        ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        v = feat[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
        return jnp.where(ok, w, 0.0) * v

    return (corner(y0, x0, (1 - ly) * (1 - lx))
            + corner(y0, x0 + 1, (1 - ly) * lx)
            + corner(y0 + 1, x0, ly * (1 - lx))
            + corner(y0 + 1, x0 + 1, ly * lx))


def _batch_index(boxes_num, n_rois, n_batch):
    """boxes_num [N] -> per-roi batch index [R] (static R; replaces LoD)."""
    return jnp.repeat(jnp.arange(n_batch), boxes_num,
                      total_repeat_length=n_rois)


# ---------------------------------------------------------------------------
# roi_align
# ---------------------------------------------------------------------------

def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoI Align (`python/paddle/vision/ops.py:1145`,
    `detection`-adjacent kernel `operators/roi_align_op.cu`).

    x [N,C,H,W]; boxes [R,4] xyxy in input coords; boxes_num [N] int32.
    Returns [R, C, ph, pw]. TPU note: `sampling_ratio <= 0` (adaptive
    grid, data-dependent) is replaced by a static 2x2 grid per bin so the
    op keeps static shapes under jit; pass an explicit ratio for exact
    reference-adaptive parity.
    """
    ph, pw = _pair(output_size)
    ratio = int(sampling_ratio) if sampling_ratio and sampling_ratio > 0 \
        else 2

    def fn(xv, bv, nv):
        R = bv.shape[0]
        bidx = _batch_index(nv, R, xv.shape[0])
        off = 0.5 if aligned else 0.0
        sb = bv * spatial_scale - off
        x1, y1 = sb[:, 0], sb[:, 1]
        rw = sb[:, 2] - x1
        rh = sb[:, 3] - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        # uniform sample grid: bin i, sub-sample s -> (i*ratio + s + .5)/ratio
        gy = (jnp.arange(ph * ratio) + 0.5) / ratio   # in bin_h units
        gx = (jnp.arange(pw * ratio) + 0.5) / ratio
        sy = y1[:, None] + (rh / ph)[:, None] * gy    # [R, ph*ratio]
        sx = x1[:, None] + (rw / pw)[:, None] * gx    # [R, pw*ratio]

        # point gathers straight out of [N,C,H,W] — never materialize a
        # per-roi feature-map copy (R x C x H x W would dwarf HBM at FPN
        # scale); each of the 4 corner reads is one batched gather
        H, W = xv.shape[-2:]
        yy = jnp.broadcast_to(sy[:, :, None],
                              sy.shape + (sx.shape[1],))   # [R, S, T]
        xx = jnp.broadcast_to(sx[:, None, :],
                              (sy.shape[0], sy.shape[1], sx.shape[1]))
        outside = (yy < -1.0) | (yy > H) | (xx < -1.0) | (xx > W)
        yc = jnp.clip(yy, 0.0, H - 1)
        xc = jnp.clip(xx, 0.0, W - 1)
        y0 = jnp.floor(yc).astype(jnp.int32)
        x0 = jnp.floor(xc).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, H - 1)
        x1i = jnp.minimum(x0 + 1, W - 1)
        ly, lx = yc - y0, xc - x0

        def gather4(yi, xi):
            v = xv[bidx[:, None, None], :, yi, xi]    # [R, S, T, C]
            return jnp.moveaxis(v, -1, 1)             # [R, C, S, T]

        w = lambda a: a[:, None]                      # noqa: E731
        val = (w((1 - ly) * (1 - lx)) * gather4(y0, x0)
               + w((1 - ly) * lx) * gather4(y0, x1i)
               + w(ly * (1 - lx)) * gather4(y1i, x0)
               + w(ly * lx) * gather4(y1i, x1i))
        val = jnp.where(outside[:, None], 0.0, val)
        R_, C = val.shape[:2]
        return val.reshape(R_, C, ph, ratio, pw, ratio).mean((3, 5))

    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    nv = _val(ensure_tensor(boxes_num)).astype(jnp.int32)
    return apply(lambda xv, bv: fn(xv, bv, nv), x, boxes)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


# ---------------------------------------------------------------------------
# roi_pool
# ---------------------------------------------------------------------------

def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max RoI pooling (`operators/roi_pool_op.cc` contract: integer pixel
    bins hstart=floor(i*rh/ph), hend=ceil((i+1)*rh/ph), empty bin -> 0).

    The CUDA kernel's per-bin argmax loop becomes a one-hot bin-membership
    mask over H then W with two masked max-reductions — static shapes, and
    the max's VJP routes the gradient to the argmax pixel exactly like the
    reference's saved-argmax backward.
    """
    ph, pw = _pair(output_size)

    def fn(xv, bv, nv):
        R = bv.shape[0]
        H, W = xv.shape[-2:]
        bidx = _batch_index(nv, R, xv.shape[0])
        rb = jnp.round(bv * spatial_scale).astype(jnp.int32)
        x1, y1 = rb[:, 0], rb[:, 1]
        rw = jnp.maximum(rb[:, 2] - x1 + 1, 1)
        rh = jnp.maximum(rb[:, 3] - y1 + 1, 1)

        i = jnp.arange(ph)
        j = jnp.arange(pw)
        hs = jnp.floor(i[None] * rh[:, None] / ph).astype(jnp.int32) \
            + y1[:, None]
        he = jnp.ceil((i[None] + 1) * rh[:, None] / ph).astype(jnp.int32) \
            + y1[:, None]
        ws = jnp.floor(j[None] * rw[:, None] / pw).astype(jnp.int32) \
            + x1[:, None]
        we = jnp.ceil((j[None] + 1) * rw[:, None] / pw).astype(jnp.int32) \
            + x1[:, None]
        hcoord = jnp.arange(H)
        wcoord = jnp.arange(W)
        # [R, ph, H] / [R, pw, W] bin membership
        mh = (hcoord[None, None] >= jnp.clip(hs, 0, H)[..., None]) & \
             (hcoord[None, None] < jnp.clip(he, 0, H)[..., None])
        mw = (wcoord[None, None] >= jnp.clip(ws, 0, W)[..., None]) & \
             (wcoord[None, None] < jnp.clip(we, 0, W)[..., None])

        def per_roi(args):
            # one roi at a time (lax.map bounds live memory at
            # [C, ph, H, W] instead of vmap's [R, C, ph, H, W])
            b, mhr, mwr = args
            feat = xv[b]                               # [C, H, W]
            t = jnp.where(mhr[None, :, :, None], feat[:, None], NEG_INF)
            t = t.max(2)                               # [C, ph, W]
            o = jnp.where(mwr[None, None], t[:, :, None], NEG_INF).max(3)
            return jnp.where(o <= NEG_INF / 2, 0.0, o)  # [C, ph, pw]

        return jax.lax.map(per_roi, (bidx, mh, mw))

    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    nv = _val(ensure_tensor(boxes_num)).astype(jnp.int32)
    return apply(lambda xv, bv: fn(xv, bv, nv), x, boxes)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


# ---------------------------------------------------------------------------
# psroi_pool
# ---------------------------------------------------------------------------

def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (`operators/psroi_pool_op.cc`):
    input channel (c*ph + i)*pw + j feeds output channel c at bin (i, j);
    bins are floor/ceil integer ranges of the scaled roi, empty bin -> 0.
    """
    ph, pw = _pair(output_size)

    def fn(xv, bv, nv):
        R = bv.shape[0]
        N, C, H, W = xv.shape
        assert C % (ph * pw) == 0, \
            f"psroi_pool: channels {C} not divisible by {ph}*{pw}"
        oc = C // (ph * pw)
        bidx = _batch_index(nv, R, N)
        # reference order of operations: round the box IN INPUT COORDS,
        # end pixel inclusive (+1), THEN scale (`psroi_pool_op.cc`)
        x1 = jnp.round(bv[:, 0]) * spatial_scale
        y1 = jnp.round(bv[:, 1]) * spatial_scale
        rw = jnp.maximum(
            (jnp.round(bv[:, 2]) + 1.0) * spatial_scale - x1, 0.1)
        rh = jnp.maximum(
            (jnp.round(bv[:, 3]) + 1.0) * spatial_scale - y1, 0.1)

        i = jnp.arange(ph)
        j = jnp.arange(pw)
        hs = jnp.floor(y1[:, None] + i[None] * rh[:, None] / ph)
        he = jnp.ceil(y1[:, None] + (i[None] + 1) * rh[:, None] / ph)
        ws = jnp.floor(x1[:, None] + j[None] * rw[:, None] / pw)
        we = jnp.ceil(x1[:, None] + (j[None] + 1) * rw[:, None] / pw)
        hcoord = jnp.arange(H)
        wcoord = jnp.arange(W)
        mh = (hcoord[None, None] >= jnp.clip(hs, 0, H)[..., None]) & \
             (hcoord[None, None] < jnp.clip(he, 0, H)[..., None])
        mw = (wcoord[None, None] >= jnp.clip(ws, 0, W)[..., None]) & \
             (wcoord[None, None] < jnp.clip(we, 0, W)[..., None])

        def per_roi(args):
            b, mhr, mwr = args
            f = xv[b].reshape(oc, ph, pw, H, W)   # position-sensitive view
            m = mhr[:, None, :, None] * mwr[None, :, None, :]  # [ph,pw,H,W]
            s = (f * m[None]).sum((3, 4))
            cnt = m.sum((2, 3))
            return jnp.where(cnt[None] > 0, s / jnp.maximum(cnt[None], 1),
                             0.0)

        return jax.lax.map(
            per_roi, (bidx, mh.astype(xv.dtype), mw.astype(xv.dtype)))

    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    nv = _val(ensure_tensor(boxes_num)).astype(jnp.int32)
    return apply(lambda xv, bv: fn(xv, bv, nv), x, boxes)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


# ---------------------------------------------------------------------------
# deform_conv2d
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (`python/paddle/vision/ops.py:423`,
    `operators/deformable_conv_op.cu`).

    The reference's modulated-im2col CUDA kernel becomes: bilinear-sample
    the input at (grid + offset) for every kernel tap -> columns
    [N, Cin*kh*kw, Ho*Wo] -> grouped matmul with the flattened weight.
    The matmul is the MXU-friendly part; sampling is 4 gathers per tap.
    mask=None is v1; mask [N, dg*kh*kw, Ho, Wo] is v2 modulation.
    """
    sh, sw = _pair(stride)
    dh, dw = _pair(dilation)
    pad = _pair(padding)

    def fn(*vals):
        if mask is None:
            xv, ov, wv = vals[:3]
            mv = None
            rest = vals[3:]
        else:
            xv, ov, wv, mv = vals[:4]
            rest = vals[4:]
        bv = rest[0] if rest else None
        N, Cin, H, W = xv.shape
        Cout, Cin_g, kh, kw = wv.shape
        Ho = (H + 2 * pad[0] - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pad[1] - dw * (kw - 1) - 1) // sw + 1
        dg = deformable_groups
        K = kh * kw

        # base sampling grid, padded coords: p0 + kernel tap offset
        oy = jnp.arange(Ho) * sh - pad[0]
        ox = jnp.arange(Wo) * sw - pad[1]
        ky = jnp.arange(kh) * dh
        kx = jnp.arange(kw) * dw
        # offsets: [N, dg, K, 2, Ho, Wo] with (dy, dx) interleaved per tap
        off = ov.reshape(N, dg, K, 2, Ho, Wo)
        py = (oy[None, None, None, :, None] +
              jnp.repeat(ky, kw)[None, None, :, None, None] +
              off[:, :, :, 0])                       # [N, dg, K, Ho, Wo]
        px = (ox[None, None, None, None, :] +
              jnp.tile(kx, kh)[None, None, :, None, None] +
              off[:, :, :, 1])

        xg = xv.reshape(N, dg, Cin // dg, H, W)

        def sample_one(feat, yy, xx):
            # feat [C', H, W], yy/xx [K, Ho, Wo] -> [C', K, Ho, Wo]
            return _bilinear_zero(feat, yy, xx)

        samp = jax.vmap(jax.vmap(sample_one))(xg, py, px)
        # [N, dg, Cin/dg, K, Ho, Wo]
        if mv is not None:
            m = mv.reshape(N, dg, 1, K, Ho, Wo)
            samp = samp * m
        cols = samp.reshape(N, Cin * K, Ho * Wo)

        # grouped matmul: weight [Cout, Cin/g*K]
        wcol = wv.reshape(groups, Cout // groups, Cin_g * K)
        cg = cols.reshape(N, groups, (Cin // groups) * K, Ho * Wo)
        out = jnp.einsum("gok,ngkp->ngop", wcol, cg,
                         preferred_element_type=jnp.float32)
        out = out.reshape(N, Cout, Ho, Wo).astype(xv.dtype)
        if bv is not None:
            out = out + bv.reshape(1, -1, 1, 1)
        return out

    tensors = [ensure_tensor(x), ensure_tensor(offset), ensure_tensor(weight)]
    if mask is not None:
        tensors.append(ensure_tensor(mask))
    if bias is not None:
        tensors.append(ensure_tensor(bias))
    return apply(fn, *tensors)


class DeformConv2D(Layer):
    """`python/paddle/vision/ops.py:626` DeformConv2D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = _pair(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        fan_in = in_channels * kh * kw
        bound = 1.0 / math.sqrt(fan_in)
        from ..nn.initializer import Uniform
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, kh, kw],
            attr=weight_attr, default_initializer=Uniform(-bound, bound))
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self._stride, self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes/scores
    (`python/paddle/vision/ops.py:252`, `operators/detection/yolo_box_op.h`).

    x [N, A*(5+nc), H, W] — or [N, A*(6+nc), H, W] when iou_aware: the
    FIRST A channels hold per-anchor IoU predictions (reference
    GetIoUIndex layout) and confidence becomes
    obj^(1-iou_aware_factor) * iou^iou_aware_factor (yolo_box_op.h:151).
    img_size [N, 2] (h, w).
    Returns (boxes [N, A*H*W, 4] xyxy image pixels, scores [N, A*H*W, nc]);
    predictions with objectness < conf_thresh are zeroed (the reference's
    LoD-less "score=0" convention — fixed shapes, no compaction).
    """
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)

    def fn(xv, imv):
        N, C, H, W = xv.shape
        A = anchors.shape[0]
        nc = class_num
        if iou_aware:
            assert C == A * (6 + nc), f"yolo_box: C={C} != A*(6+nc)"
            iou = jax.nn.sigmoid(xv[:, :A])          # [N, A, H, W]
            xv = xv[:, A:]
        else:
            assert C == A * (5 + nc), f"yolo_box: C={C} != A*(5+nc)"
        t = xv.reshape(N, A, 5 + nc, H, W)
        input_size = downsample_ratio * H
        gx = jnp.arange(W, dtype=xv.dtype)
        gy = jnp.arange(H, dtype=xv.dtype)
        bias = 0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(t[:, :, 0]) * scale_x_y - bias
              + gx[None, None, None, :]) / W
        cy = (jax.nn.sigmoid(t[:, :, 1]) * scale_x_y - bias
              + gy[None, None, :, None]) / H
        aw = jnp.asarray(anchors[:, 0])[None, :, None, None]
        ah = jnp.asarray(anchors[:, 1])[None, :, None, None]
        bw = jnp.exp(t[:, :, 2]) * aw / input_size
        bh = jnp.exp(t[:, :, 3]) * ah / input_size
        conf = jax.nn.sigmoid(t[:, :, 4])
        if iou_aware:
            conf = (jnp.power(conf, 1.0 - iou_aware_factor)
                    * jnp.power(iou, iou_aware_factor))
        on = conf >= conf_thresh
        imh = imv[:, 0].astype(xv.dtype)[:, None, None, None]
        imw = imv[:, 1].astype(xv.dtype)[:, None, None, None]
        x1 = (cx - bw / 2) * imw
        y1 = (cy - bh / 2) * imh
        x2 = (cx + bw / 2) * imw
        y2 = (cy + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, imw - 1)
            y1 = jnp.clip(y1, 0.0, imh - 1)
            x2 = jnp.clip(x2, 0.0, imw - 1)
            y2 = jnp.clip(y2, 0.0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1)      # [N, A, H, W, 4]
        boxes = jnp.where(on[..., None], boxes, 0.0)
        scores = conf[..., None] * jax.nn.sigmoid(
            jnp.moveaxis(t[:, :, 5:], 2, -1))        # [N, A, H, W, nc]
        scores = jnp.where(on[..., None], scores, 0.0)
        return (boxes.reshape(N, A * H * W, 4),
                scores.reshape(N, A * H * W, nc))

    return apply(lambda xv, iv: fn(xv, iv), ensure_tensor(x),
                 ensure_tensor(img_size))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (`python/paddle/vision/ops.py:42`,
    `operators/detection/yolov3_loss_op.h`). Per-sample loss [N].

    Contract (matching the reference kernel):
    - each gt picks its best anchor by wh-IoU over ALL anchors; the gt is
      assigned only if that anchor is in `anchor_mask`, at the cell it
      falls in;
    - location loss = SCE(tx,ty) + L1(tw,th), scaled by (2 - w*h)*score;
    - objectness: positives SCE(obj,1)*score; negatives SCE(obj,0) except
      predictions whose best IoU over gts exceeds ignore_thresh;
    - class loss = SCE with optional label smoothing (eps = min(1/nc,1/40)).
    The per-gt scatter loops of the kernel become one-hot masks reduced
    over the (batch, gt) axes — everything static-shape, grads flow
    through jax.vjp of this function (no hand-written backward needed).
    """
    anchors_np = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_np = np.asarray(anchor_mask, np.int32)

    def fn(xv, gbv, glv, gsv):
        N, C, H, W = xv.shape
        A = mask_np.shape[0]
        nc = class_num
        assert C == A * (5 + nc), f"yolo_loss: C={C} != A_mask*(5+nc)"
        t = xv.reshape(N, A, 5 + nc, H, W)
        input_size = downsample_ratio * H
        B = gbv.shape[1]

        gx, gy = gbv[..., 0], gbv[..., 1]            # [N, B] normalized
        gw, gh = gbv[..., 2], gbv[..., 3]
        valid = (gw > 0) & (gh > 0)

        # best anchor per gt: wh-IoU vs all anchors at origin
        aw = anchors_np[:, 0] / input_size
        ah = anchors_np[:, 1] / input_size
        inter = jnp.minimum(gw[..., None], aw) * jnp.minimum(gh[..., None],
                                                             ah)
        union = gw[..., None] * gh[..., None] + aw * ah - inter
        best_a = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)  # [N,B]
        # map into the mask; -1 when not in this head's mask
        in_mask = (best_a[..., None] == mask_np).astype(jnp.int32)
        a_pos = jnp.where(in_mask.sum(-1) > 0,
                          jnp.argmax(in_mask, -1), -1)              # [N,B]
        assigned = valid & (a_pos >= 0)

        gi = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)
        tx = gx * W - gi
        ty = gy * H - gj
        aw_sel = anchors_np[:, 0][jnp.clip(best_a, 0, None)]
        ah_sel = anchors_np[:, 1][jnp.clip(best_a, 0, None)]
        tw = jnp.log(jnp.maximum(gw * input_size / aw_sel, 1e-9))
        th = jnp.log(jnp.maximum(gh * input_size / ah_sel, 1e-9))
        scale = (2.0 - gw * gh) * gsv                               # [N,B]

        def sce(logit, label):
            return jnp.maximum(logit, 0) - logit * label + \
                jnp.log1p(jnp.exp(-jnp.abs(logit)))

        # gather each gt's prediction vector at its (a, gj, gi) cell —
        # [N, B, 5+nc] instead of broadcasting losses over the whole
        # [N, B, A, nc, H, W] grid (which is ~GBs at 52x52/80-class scale)
        def gather_gt(tn, ap, gjn, gin):
            return tn[jnp.clip(ap, 0, A - 1), :, gjn, gin]  # [B, 5+nc]

        pg = jax.vmap(gather_gt)(t, a_pos, gj, gi)
        amask = assigned.astype(xv.dtype)
        loc = (sce(pg[..., 0], tx) + sce(pg[..., 1], ty)
               + jnp.abs(pg[..., 2] - tw) + jnp.abs(pg[..., 3] - th))
        loc_loss = (loc * amask * scale).sum(1)

        if use_label_smooth:
            eps = min(1.0 / nc, 1.0 / 40.0)
            pos_l, neg_l = 1.0 - eps, eps
        else:
            pos_l, neg_l = 1.0, 0.0
        cls_target = jnp.where(
            (glv[..., None] == jnp.arange(nc)), pos_l, neg_l)  # [N,B,nc]
        cls = sce(pg[..., 5:], cls_target)
        cls_loss = (cls * (amask * gsv)[..., None]).sum((1, 2))

        # positive-cell scatter for the objectness term (flat [A*H*W]
        # grid per sample; unassigned gts index off the end and drop)
        flat_cell = (jnp.clip(a_pos, 0, A - 1) * H + gj) * W + gi
        flat_cell = jnp.where(assigned, flat_cell, A * H * W)
        nidx = jnp.broadcast_to(jnp.arange(N)[:, None], flat_cell.shape)
        is_pos = jnp.zeros((N, A * H * W), xv.dtype).at[
            nidx, flat_cell].max(1.0, mode="drop").reshape(N, A, H, W)
        obj_pos = jnp.zeros((N, A * H * W), xv.dtype).at[
            nidx, flat_cell].add(gsv, mode="drop").reshape(N, A, H, W)

        # objectness: decode pred boxes, iou vs gts for the ignore mask
        bias = 0.5 * (scale_x_y - 1.0)
        px = (jax.nn.sigmoid(t[:, :, 0]) * scale_x_y - bias
              + jnp.arange(W)[None, None, None, :]) / W
        py = (jax.nn.sigmoid(t[:, :, 1]) * scale_x_y - bias
              + jnp.arange(H)[None, None, :, None]) / H
        maw = anchors_np[mask_np, 0]
        mah = anchors_np[mask_np, 1]
        pw = jnp.exp(t[:, :, 2]) * maw[None, :, None, None] / input_size
        phh = jnp.exp(t[:, :, 3]) * mah[None, :, None, None] / input_size
        pb = jnp.stack([px - pw / 2, py - phh / 2,
                        px + pw / 2, py + phh / 2], -1)  # [N,A,H,W,4]
        gb = jnp.stack([gx - gw / 2, gy - gh / 2,
                        gx + gw / 2, gy + gh / 2], -1)   # [N,B,4]

        def per_sample_iou(pbv, gbv2, vv):
            m = iou_matrix(pbv.reshape(-1, 4), gbv2)     # [AHW, B]
            m = jnp.where(vv[None], m, 0.0)
            return m.max(-1).reshape(A, H, W)

        best_iou = jax.vmap(per_sample_iou)(pb, gb, valid)
        ignore = (best_iou > ignore_thresh) & (is_pos < 0.5)
        obj_logit = t[:, :, 4]
        obj_loss = jnp.where(
            is_pos > 0.5, sce(obj_logit, 1.0) * obj_pos,
            jnp.where(ignore, 0.0, sce(obj_logit, 0.0)))
        obj_loss = obj_loss.sum((1, 2, 3))

        return loc_loss + cls_loss + obj_loss

    x = ensure_tensor(x)
    gt_box = ensure_tensor(gt_box)
    gt_label = ensure_tensor(gt_label)
    if gt_score is None:
        gs = jnp.ones(_val(gt_label).shape, jnp.float32)
    else:
        gs = _val(ensure_tensor(gt_score))
    glv = _val(gt_label).astype(jnp.int32)
    return apply(lambda xv, gbv: fn(xv, gbv, glv, gs), x, gt_box)


# ---------------------------------------------------------------------------
# nms (single-class primitive)
# ---------------------------------------------------------------------------

def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Returns kept indices, score-descending, as a Tensor.

    Matches `paddle.vision.ops.nms`: with `categories`, suppression only
    happens within a category (implemented by offsetting each category's
    boxes to a disjoint coordinate range — one fused NMS instead of a
    per-category loop). NOTE (TPU contract): when `top_k` is given the
    result is a static-shape [top_k] index array padded with -1; without
    top_k the kept count is data-dependent, so the compaction runs on
    host (eager only).
    """
    b = _val(ensure_tensor(boxes)).astype(jnp.float32)
    m = b.shape[0]
    s = (jnp.arange(m, 0, -1, dtype=jnp.float32) if scores is None
         else _val(ensure_tensor(scores)).astype(jnp.float32))
    if category_idxs is not None:
        cidx = _val(ensure_tensor(category_idxs)).astype(jnp.int32)
        span = jnp.max(b) - jnp.min(b) + 1.0
        b = b + (cidx[:, None] * span).astype(b.dtype)
    keep, order = nms_mask(b, s, iou_threshold)
    kept_sorted = keep[order]                        # in score order
    if top_k is not None:
        rank = jnp.cumsum(kept_sorted.astype(jnp.int32)) - 1
        out = jnp.full((top_k,), -1, jnp.int32)
        put = jnp.where(kept_sorted & (rank < top_k), rank, top_k)
        out = out.at[put].set(order.astype(jnp.int32), mode="drop")
        return Tensor(out)
    idx = np.asarray(order)[np.asarray(kept_sorted)]
    return Tensor(jnp.asarray(idx, jnp.int32))


# ---- image file ops (reference `python/paddle/vision/ops.py:819,864`
# read_file / decode_jpeg — there backed by a CUDA nvjpeg kernel) ------

def read_file(filename, name=None):
    """Raw file bytes as a 1-D uint8 tensor (reference `read_file`).
    Host-side: file IO feeds the input pipeline, not the chip."""
    import numpy as _np
    from ..core.tensor import Tensor as _T
    with open(filename, "rb") as f:
        data = f.read()
    return _T(jnp.asarray(_np.frombuffer(data, _np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to a CHW uint8 tensor (reference
    `decode_jpeg`, nvjpeg kernel; PIL does the host-side decode here —
    decode is data-pipeline work, the chip sees dense batches).
    mode: 'unchanged' | 'gray' | 'rgb'."""
    import io as _io
    import numpy as _np
    from PIL import Image
    from ..core.tensor import Tensor as _T
    raw = bytes(_np.asarray(x._value if hasattr(x, "_value") else x,
                            _np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = _np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]                  # [1, H, W]
    else:
        arr = arr.transpose(2, 0, 1)     # HWC -> CHW
    return _T(jnp.asarray(arr))
