"""Shared box arithmetic for the detection op family.

TPU-first counterparts of the reference's header helpers
(`paddle/fluid/operators/detection/bbox_util.h`,
`detection/nms_util.h`): everything is fixed-shape and vectorized —
IoU as one broadcasted matrix op for the MXU/VPU, greedy NMS as a
`lax.fori_loop` whose per-step work is a fully vectorized mask update
(no data-dependent shapes anywhere, so all of it jits on TPU).
"""
import jax
import jax.numpy as jnp

NEG_INF = -1e10


def box_area(boxes, normalized=True):
    """[.., 4] xyxy -> [..]; +1 pixel convention when not normalized
    (reference `bbox_util.h` BBoxArea)."""
    off = 0.0 if normalized else 1.0
    w = boxes[..., 2] - boxes[..., 0] + off
    h = boxes[..., 3] - boxes[..., 1] + off
    return jnp.where((w >= 0) & (h >= 0), w * h, 0.0)


def iou_matrix(a, b, normalized=True):
    """a [N,4], b [M,4] xyxy -> IoU [N,M] (reference
    `detection/iou_similarity_op.h` IOUSimilarity)."""
    off = 0.0 if normalized else 1.0
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(a, normalized)[:, None] + \
        box_area(b, normalized)[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def nms_mask(boxes, scores, iou_threshold, normalized=True, eta=1.0,
             valid=None):
    """Greedy hard-NMS over M already-materialized candidates.

    Returns (keep [M] bool in ORIGINAL order, order [M] score-desc indices).
    The sequential dependency of greedy NMS (reference
    `detection/nms_util.h` NMSFast) is kept, but each of the M steps is a
    vectorized mask update against the precomputed IoU row — O(M) scan
    steps of O(M) vector work, static shapes throughout. `eta` < 1 shrinks
    the threshold adaptively after each kept box once it exceeds 0.5
    (reference adaptive-NMS semantics).
    """
    m = boxes.shape[0]
    order = jnp.argsort(-scores)
    sb = boxes[order]
    iou = iou_matrix(sb, sb, normalized)
    v = jnp.ones((m,), bool) if valid is None else valid[order]

    def body(i, carry):
        keep, thresh = carry
        kept_before = keep & (jnp.arange(m) < i)
        suppressed = jnp.any(kept_before & (iou[i] > thresh))
        k = (~suppressed) & v[i]
        keep = keep.at[i].set(k)
        shrink = k & (eta < 1.0) & (thresh > 0.5)
        thresh = jnp.where(shrink, thresh * eta, thresh)
        return keep, thresh

    keep_sorted, _ = jax.lax.fori_loop(
        0, m, body, (jnp.zeros((m,), bool), jnp.asarray(iou_threshold,
                                                        jnp.float32)))
    keep = jnp.zeros((m,), bool).at[order].set(keep_sorted)
    return keep, order
