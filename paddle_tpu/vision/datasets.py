"""Vision datasets — parity with `python/paddle/vision/datasets/`.

Zero-egress environment: loaders read from local files when present
(`image_path`/`label_path` args, standard IDX/pickle formats) and raise a
clear error otherwise; `FakeData`/`SyntheticMNIST` provide deterministic
generated data for tests and benchmarks.
"""
import gzip
import os
import pickle
import struct

import numpy as np

from ..io.dataloader import Dataset


class MNIST(Dataset):
    """IDX-format MNIST from local files (reference downloads;
    zero-egress here)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None, root=None):
        self.transform = transform
        prefix = "train" if mode == "train" else "t10k"
        root = root or os.environ.get("MNIST_DATA_ROOT", "")
        image_path = image_path or os.path.join(
            root, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            root, f"{prefix}-labels-idx1-ubyte.gz")
        if not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise FileNotFoundError(
                f"MNIST files not found ({image_path}); this environment has "
                "no network access — provide local files or use "
                "paddle_tpu.vision.datasets.SyntheticMNIST")
        self.images = self._read_idx(image_path, 16).reshape(-1, 28, 28)
        self.labels = self._read_idx(label_path, 8)

    @staticmethod
    def _read_idx(path, header):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            data = f.read()
        return np.frombuffer(data, dtype=np.uint8, offset=header)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.labels)


FashionMNIST = MNIST


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "CIFAR batches not found; zero-egress environment — pass "
                "data_file or use FakeData")
        with open(data_file, "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        self.images = batch[b"data"].reshape(-1, 3, 32, 32).transpose(
            0, 2, 3, 1)
        self.labels = batch.get(b"labels", batch.get(b"fine_labels"))

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    pass


class FakeData(Dataset):
    """Deterministic synthetic image-classification data (for tests/bench)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = idx % self.num_classes
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size


class SyntheticMNIST(Dataset):
    """Learnable synthetic MNIST-shaped data: class encoded in a patch."""

    def __init__(self, size=1024, transform=None, seed=0):
        rng = np.random.RandomState(seed)
        self.images = rng.rand(size, 1, 28, 28).astype(np.float32)
        self.labels = rng.randint(0, 10, size)
        for i in range(size):
            self.images[i, 0, :8, :8] = self.labels[i] / 10.0
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)


class DatasetFolder(Dataset):
    """Directory-per-class image dataset (reference
    `vision/datasets/folder.py` DatasetFolder): root/<class>/<img>."""

    IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".webp")

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(e.lower() for e in (extensions or self.IMG_EXTS))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise FileNotFoundError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    path = os.path.join(dirpath, fn)
                    ok = (is_valid_file(path) if is_valid_file
                          else fn.lower().endswith(exts))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise FileNotFoundError(f"no images under {root}")

    @staticmethod
    def _default_loader(path):
        from PIL import Image
        with Image.open(path) as img:
            return np.asarray(img.convert("RGB"))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive image collection without labels (reference
    `vision/datasets/folder.py` ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        exts = tuple(e.lower() for e in
                     (extensions or DatasetFolder.IMG_EXTS))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                path = os.path.join(dirpath, fn)
                ok = (is_valid_file(path) if is_valid_file
                      else fn.lower().endswith(exts))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise FileNotFoundError(f"no images under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(DatasetFolder):
    """Flowers102 from a local extracted copy (reference downloads;
    zero-egress here: point `root`/FLOWERS_DATA_ROOT at a class-per-dir
    layout)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None,
                 root=None):
        # reference signature (`vision/datasets/flowers.py`): explicit
        # archive paths. A data_file pointing at an extracted class-per-
        # dir tree works as root here; label/setid files are part of the
        # .mat archive layout this build does not parse.
        if label_file or setid_file:
            raise NotImplementedError(
                "Flowers: .mat label/setid archives are not parsed in "
                "this build; point data_file/root at an extracted "
                "class-per-directory tree")
        root = root or data_file or os.environ.get("FLOWERS_DATA_ROOT", "")
        if not root or not os.path.isdir(root):
            raise FileNotFoundError(
                "Flowers data not found; this environment has no network "
                "access — set FLOWERS_DATA_ROOT to an extracted copy or "
                "use FakeData")
        super().__init__(root, transform=transform)


class VOC2012(Dataset):
    """VOC2012 segmentation pairs from a local VOCdevkit (reference
    downloads; zero-egress here)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, root=None):
        # reference signature (`vision/datasets/voc2012.py`): data_file
        # is the archive path — an extracted VOCdevkit dir works here
        root = root or data_file or os.environ.get("VOC_DATA_ROOT", "")
        base = os.path.join(root, "VOC2012")
        lists = os.path.join(base, "ImageSets", "Segmentation",
                             f"{'train' if mode == 'train' else 'val'}.txt")
        if not os.path.exists(lists):
            raise FileNotFoundError(
                "VOC2012 not found; set VOC_DATA_ROOT to a VOCdevkit "
                "directory (no network access in this environment)")
        names = [l.strip() for l in open(lists) if l.strip()]
        self.pairs = [
            (os.path.join(base, "JPEGImages", f"{n}.jpg"),
             os.path.join(base, "SegmentationClass", f"{n}.png"))
            for n in names]
        self.transform = transform

    def __getitem__(self, idx):
        from PIL import Image
        ip, lp = self.pairs[idx]
        img = np.asarray(Image.open(ip).convert("RGB"))
        lbl = np.asarray(Image.open(lp))
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.pairs)
