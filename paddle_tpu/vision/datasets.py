"""Vision datasets — parity with `python/paddle/vision/datasets/`.

Zero-egress environment: loaders read from local files when present
(`image_path`/`label_path` args, standard IDX/pickle formats) and raise a
clear error otherwise; `FakeData`/`SyntheticMNIST` provide deterministic
generated data for tests and benchmarks.
"""
import gzip
import os
import pickle
import struct

import numpy as np

from ..io.dataloader import Dataset


class MNIST(Dataset):
    """IDX-format MNIST from local files (reference downloads;
    zero-egress here)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None, root=None):
        self.transform = transform
        prefix = "train" if mode == "train" else "t10k"
        root = root or os.environ.get("MNIST_DATA_ROOT", "")
        image_path = image_path or os.path.join(
            root, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            root, f"{prefix}-labels-idx1-ubyte.gz")
        if not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise FileNotFoundError(
                f"MNIST files not found ({image_path}); this environment has "
                "no network access — provide local files or use "
                "paddle_tpu.vision.datasets.SyntheticMNIST")
        self.images = self._read_idx(image_path, 16).reshape(-1, 28, 28)
        self.labels = self._read_idx(label_path, 8)

    @staticmethod
    def _read_idx(path, header):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            data = f.read()
        return np.frombuffer(data, dtype=np.uint8, offset=header)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.labels)


FashionMNIST = MNIST


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "CIFAR batches not found; zero-egress environment — pass "
                "data_file or use FakeData")
        with open(data_file, "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        self.images = batch[b"data"].reshape(-1, 3, 32, 32).transpose(
            0, 2, 3, 1)
        self.labels = batch.get(b"labels", batch.get(b"fine_labels"))

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    pass


class FakeData(Dataset):
    """Deterministic synthetic image-classification data (for tests/bench)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = idx % self.num_classes
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size


class SyntheticMNIST(Dataset):
    """Learnable synthetic MNIST-shaped data: class encoded in a patch."""

    def __init__(self, size=1024, transform=None, seed=0):
        rng = np.random.RandomState(seed)
        self.images = rng.rand(size, 1, 28, 28).astype(np.float32)
        self.labels = rng.randint(0, 10, size)
        for i in range(size):
            self.images[i, 0, :8, :8] = self.labels[i] / 10.0
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)
