from . import dtype, autograd, random, tensor  # noqa: F401
from .tensor import Tensor, Parameter, apply, to_tensor  # noqa: F401
from .autograd import no_grad, enable_grad, set_grad_enabled, grad  # noqa: F401
from .random import seed, default_generator, get_rng_state_tracker  # noqa: F401
