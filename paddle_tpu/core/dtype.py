"""Dtype system for paddle_tpu.

TPU-native replacement for the reference's dtype enum
(`/root/reference/paddle/fluid/framework/framework.proto:117` VarType and
`paddle/fluid/framework/data_type.h`). Canonical dtypes are numpy dtypes
(bfloat16 via ml_dtypes, which JAX re-exports); bf16 is the *default compute
policy* on TPU rather than an AMP afterthought.
"""
import numpy as np
import jax.numpy as jnp

# canonical dtype singletons (numpy dtype objects)
bool = np.dtype("bool")  # noqa: A001 - mirrors paddle.bool
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = np.dtype(jnp.bfloat16)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_ALIASES = {
    "bool": bool, "uint8": uint8, "int8": int8, "int16": int16,
    "int32": int32, "int64": int64, "float16": float16, "fp16": float16,
    "bfloat16": bfloat16, "bf16": bfloat16, "float32": float32,
    "fp32": float32, "float64": float64, "fp64": float64,
    "complex64": complex64, "complex128": complex128, "float": float32,
    "double": float64, "int": int32, "long": int64, "half": float16,
}

_default_dtype = float32


def _demote_64(dtype):
    """When jax x64 is off (the TPU-native default: 64-bit is slow and rarely
    wanted on TPU), silently canonicalize 64-bit requests to 32-bit rather
    than warn on every index op."""
    import jax
    if jax.config.jax_enable_x64:
        return dtype
    if dtype == int64:
        return int32
    if dtype == float64:
        return float32
    if dtype == complex128:
        return complex64
    return dtype


def convert_dtype(dtype):
    """Normalize str/np.dtype/jnp type/python type to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, np.dtype):
        return _demote_64(dtype)
    if isinstance(dtype, str):
        try:
            return _demote_64(_ALIASES[dtype])
        except KeyError:
            raise ValueError(f"unsupported dtype string: {dtype!r}") from None
    return _demote_64(np.dtype(dtype))


def set_default_dtype(d):
    # param named `d` for reference signature parity
    # (`framework/framework.py` set_default_dtype(d))
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(f"default dtype must be floating, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


def is_floating(dtype):
    return np.issubdtype(convert_dtype(dtype), np.floating) or convert_dtype(dtype) == bfloat16


def is_integer(dtype):
    return np.issubdtype(convert_dtype(dtype), np.integer)
