"""Stateful RNG facade over JAX's functional PRNG.

The reference uses per-device stateful generators
(`/root/reference/paddle/fluid/framework/generator.cc`, python `paddle.seed`,
and the model-parallel RNG tracker
`python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py`).
JAX PRNG is functional (explicit keys), so we keep a global Generator that
splits a fresh subkey per call — eager code gets paddle's stateful feel.

Inside a `to_static`/jit-traced function the global key would be baked in as a
constant (same dropout mask every step). `rng_guard(key)` threads a *traced*
key through instead: jitted train steps pass a per-step key and all random ops
inside draw from it. `RNGStatesTracker` reproduces the model-parallel seed
discipline (same dropout mask inside a TP group where activations are
replicated, different where they are sharded).
"""
import contextlib
import threading

import jax


class Generator:
    """Key creation is LAZY: importing paddle_tpu must not initialize the
    XLA backend, or `distributed.init_distributed` (which must run before
    any backend touch — jax.distributed contract) could never be called
    after the import."""

    def __init__(self, seed=0):
        self._key = None
        self._seed = seed

    def manual_seed(self, seed):
        # stay lazy: seeding must also be legal before backend init
        # (`paddle.seed(42)` before `init_distributed()` is common)
        self._key = None
        self._seed = seed
        return self

    @property
    def initial_seed(self):
        return self._seed

    def split(self):
        """Return a fresh subkey, advancing internal state."""
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        return self._key

    def set_state(self, key):
        self._key = key


class _RngState(threading.local):
    def __init__(self):
        self.generator = Generator(0)
        self.override = None  # traced key stack for jitted regions


_state = _RngState()


def seed(seed):
    """paddle.seed analog (`framework/random.py` — same param name)."""
    _state.generator.manual_seed(int(seed))
    return _state.generator


def default_generator():
    return _state.generator


def next_key():
    """Fresh PRNG subkey for one random op."""
    if _state.override is not None:
        key, sub = jax.random.split(_state.override)
        _state.override = key
        return sub
    return _state.generator.split()


@contextlib.contextmanager
def rng_guard(key):
    """Thread an explicit (possibly traced) key through random ops — used by
    jitted train steps and by the MP rng tracker."""
    prev = _state.override
    _state.override = key
    try:
        yield
    finally:
        _state.override = prev


class RNGStatesTracker:
    """Model-parallel RNG tracker — analog of
    `meta_parallel/parallel_layers/random.py` model_parallel_rng tracker."""

    def __init__(self):
        self.states = {}

    def add(self, name, seed_):
        if name in self.states:
            raise ValueError(f"rng state {name} already exists")
        self.states[name] = jax.random.PRNGKey(int(seed_))

    @contextlib.contextmanager
    def rng_state(self, name="model_parallel_rng"):
        if name not in self.states:
            raise ValueError(f"unknown rng state {name}")
        key, sub = jax.random.split(self.states[name])
        self.states[name] = key
        with rng_guard(sub):
            yield


_mp_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _mp_tracker
