"""Define-by-run autograd engine on JAX.

TPU-native replacement for the reference's imperative autograd
(`/root/reference/paddle/fluid/imperative/basic_engine.cc:39,251,379` BasicEngine
and `tracer.cc:146,235` grad-node recording). Instead of recording OpBase grad
nodes that later dispatch CUDA kernels, every eager op records a `jax.vjp`
closure on a thread-local tape; `Tensor.backward()` walks the tape in reverse
creation order (the tape is already topologically sorted, so no dep-counting
pass like PrepareDeps is needed) and accumulates cotangents.

The key TPU design win: all of this machinery runs at *trace time* under
`jax.jit`, so a whole train step (forward + backward + optimizer update)
compiles to a single fused XLA program — the reference needed a second world
(static graph + append_backward, `python/paddle/fluid/backward.py:1390`) to get
that; here eager and compiled are one code path.
"""
import contextlib
import threading

import jax
import jax.numpy as jnp
from jax.dtypes import float0


class _AutogradState(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.nodes = []  # the tape, in op-creation (topological) order


_state = _AutogradState()


def grad_enabled():
    return _state.grad_enabled


@contextlib.contextmanager
def no_grad():
    """Analog of paddle.no_grad / dygraph no_grad (`fluid/dygraph/base.py`)."""
    prev = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


def set_grad_enabled(mode):
    prev = _state.grad_enabled
    _state.grad_enabled = not not mode
    return prev


class Node:
    """One recorded op: inputs, outputs, and its reverse rule.

    Analog of `imperative::OpBase` + GradOpNode (`imperative/op_base.h`) with
    the grad kernel replaced by a jax.vjp closure.

    Gradient routing is keyed by each tensor's `_key` — a fresh object per
    *value*, not per Tensor object — captured at record time. In-place ops
    (`__setitem__`, `increment`, `reshape_`) give the mutated tensor a fresh
    key, so cotangents for the pre- and post-mutation values route to the
    right producers (the reference tracks the same hazard with
    `TensorInplaceVersion`, `framework/tensor.h:77`).
    """

    __slots__ = ("inputs", "outputs", "vjp_fn", "multi_output",
                 "in_keys", "out_keys", "in_had_producer", "out_avals")

    def __init__(self, inputs, outputs, vjp_fn, multi_output):
        self.inputs = inputs          # tuple[Tensor]
        self.outputs = outputs        # tuple[Tensor]
        self.vjp_fn = vjp_fn
        self.multi_output = multi_output
        self.in_keys = tuple(t._key for t in inputs)
        self.out_keys = tuple(o._key for o in outputs)
        self.in_had_producer = tuple(t._has_producer for t in inputs)
        # record-time output avals: a later in-place mutation (reshape_) can
        # change o._value's shape, but zero-cotangent fill must match the
        # shape this node actually produced
        self.out_avals = tuple((o._value.shape, o._value.dtype)
                               for o in outputs)


def record(node):
    _state.nodes.append(node)
    for o in node.outputs:
        o._has_producer = True


def tape_size():
    return len(_state.nodes)


def current_tape():
    return _state.nodes


def truncate_tape(size):
    """Drop nodes recorded after `size` (a tape_size() snapshot)."""
    del _state.nodes[size:]


@contextlib.contextmanager
def fresh_tape():
    """Push a fresh tape (used when tracing a compiled step so recorded nodes
    never leak between trace-time and eager graphs)."""
    prev = _state.nodes
    _state.nodes = []
    try:
        yield
    finally:
        _state.nodes = prev


def clear_tape():
    _state.nodes.clear()


def backward(tensor, grad=None, retain_graph=False):
    """Reverse-mode over the tape. Analog of BasicEngine::Execute
    (`imperative/basic_engine.cc:379`) + GradientAccumulator summation
    (`gradient_accumulator.cc`)."""
    backward_multi([tensor], [grad], retain_graph)


def backward_multi(tensors, grads=None, retain_graph=False):
    """One reverse walk with every root's cotangent seeded up front —
    shared subgraphs run each node's vjp once, not once per root
    (paddle.autograd.backward semantics)."""
    from .tensor import Tensor

    if grads is None:
        grads = [None] * len(tensors)

    # pending cotangents for non-leaf values, keyed by tape key (per-value
    # identity — survives in-place mutation of the Tensor object)
    pending = {}
    for tensor, grad in zip(tensors, grads):
        if grad is None:
            seed = jnp.ones_like(tensor._value)
        elif isinstance(grad, Tensor):
            seed = grad._value
        else:
            seed = jnp.asarray(grad, dtype=tensor._value.dtype)
        prev = pending.get(tensor._key)
        pending[tensor._key] = seed if prev is None else prev + seed
        if tensor._retain_grad or not tensor._has_producer:
            if not tensor.stop_gradient:
                tensor._accumulate_grad(seed)

    for node in reversed(_state.nodes):
        if not any(k in pending for k in node.out_keys):
            continue
        cots = []
        for (shape, dtype), k in zip(node.out_avals, node.out_keys):
            c = pending.pop(k, None)
            if c is None:
                c = jnp.zeros(shape, dtype)
            elif c.dtype != dtype:
                # accumulation across mixed-dtype consumers promotes
                # (bf16 + f32 -> f32); jax.vjp requires the cotangent in
                # the output's own dtype
                c = c.astype(dtype)
            cots.append(c)
        cot = tuple(cots) if node.multi_output else cots[0]
        in_grads = node.vjp_fn(cot)
        for inp, key, had_producer, g in zip(
                node.inputs, node.in_keys, node.in_had_producer, in_grads):
            if inp.stop_gradient or g.dtype == float0:
                continue
            if had_producer:
                prev = pending.get(key)
                pending[key] = g if prev is None else prev + g
                if inp._retain_grad:
                    inp._accumulate_grad(g)
            else:
                # leaf: accumulate into .grad (paddle accumulates across
                # backward() calls until clear_grad, varbase_patch_methods.py)
                inp._accumulate_grad(g)

    if not retain_graph:
        clear_tape()


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, only_inputs=True, allow_unused=True,
         no_grad_vars=None):
    """Analog of paddle.grad (`imperative/partial_grad_engine.cc`,
    signature parity with `fluid/dygraph/base.py` grad): grads of
    outputs w.r.t. an explicit input list, without touching .grad
    fields. only_inputs=False is unsupported in the reference too;
    no_grad_vars blocks gradient flow through the listed tensors."""
    from .tensor import Tensor

    if not only_inputs:
        raise AssertionError(
            "only_inputs=False is not supported (the reference's "
            "partial-grad engine asserts the same)")
    if isinstance(no_grad_vars, Tensor):
        no_grad_vars = [no_grad_vars]
    blocked = {id(t) for t in (no_grad_vars or [])}
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    pending = {}
    for o, g in zip(outputs, grad_outputs):
        seed = jnp.ones_like(o._value) if g is None else (
            g._value if isinstance(g, Tensor) else jnp.asarray(g))
        prev = pending.get(o._key)
        pending[o._key] = seed if prev is None else prev + seed

    # wanted is keyed by Tensor OBJECT identity: grads are w.r.t. the input
    # tensor as the graph consumed it, even if it was mutated in-place after
    # the forward pass
    wanted = {id(t): i for i, t in enumerate(inputs)}
    results = [None] * len(inputs)

    def _stash(obj_id, g):
        i = wanted.get(obj_id)
        if i is not None:
            results[i] = g if results[i] is None else results[i] + g

    for o in outputs:
        if id(o) in wanted:
            _stash(id(o), pending[o._key])

    for node in reversed(_state.nodes):
        if not any(k in pending for k in node.out_keys):
            continue
        cots = []
        for (shape, dtype), k in zip(node.out_avals, node.out_keys):
            c = pending.pop(k, None)
            if c is None:
                c = jnp.zeros(shape, dtype)
            elif c.dtype != dtype:
                # mixed-dtype consumer accumulation promotes; jax.vjp
                # requires the output's own dtype (same as backward())
                c = c.astype(dtype)
            cots.append(c)
        cot = tuple(cots) if node.multi_output else cots[0]
        in_grads = node.vjp_fn(cot)
        for inp, key, had_producer, g in zip(
                node.inputs, node.in_keys, node.in_had_producer, in_grads):
            if inp.stop_gradient or g.dtype == float0:
                continue
            if id(inp) in blocked:
                continue  # no_grad_vars: gradient does not flow through
            if had_producer:
                prev = pending.get(key)
                pending[key] = g if prev is None else prev + g
            _stash(id(inp), g)

    if not retain_graph:
        clear_tape()

    out = []
    for i, t in enumerate(inputs):
        if results[i] is None:
            if not allow_unused:
                raise RuntimeError(f"input {i} unused in the graph")
            out.append(None)
        else:
            out.append(Tensor(results[i], stop_gradient=True))
    return out
