"""Eager Tensor on JAX arrays.

TPU-native replacement for the reference's dense Tensor + dygraph VarBase
(`/root/reference/paddle/fluid/framework/tensor.h:89`,
`paddle/fluid/imperative/layer.cc` VarBase,
`python/paddle/fluid/dygraph/varbase_patch_methods.py`). A Tensor wraps a
jax.Array (device-resident, XLA-managed — the reference's Allocation/allocator
stack, `memory/allocation/allocator_facade.cc:104`, is owned by the XLA runtime
here) or a JAX tracer when executing under `paddle_tpu.jit.to_static`.

`apply()` is the single eager-dispatch point — the analog of
`imperative::Tracer::TraceOp` (`imperative/tracer.cc:146`) + PreparedOp kernel
launch (`prepared_operator.cc:92,228`): it runs the jnp/lax computation and, if
gradient is required, records a jax.vjp closure on the autograd tape.
"""
import numpy as np
import jax
import jax.numpy as jnp

from . import autograd
from .dtype import convert_dtype, get_default_dtype, bfloat16

_tensor_method_registry = {}


class Tensor:
    __slots__ = ("_value", "stop_gradient", "grad", "name", "persistable",
                 "_has_producer", "_retain_grad", "trainable", "is_distributed",
                 "_key", "__weakref__", "__dict__")

    def __init__(self, value, dtype=None, stop_gradient=True, name=None,
                 place=None):
        if isinstance(value, Tensor):
            value = value._value
        dtype = convert_dtype(dtype)
        if isinstance(value, (jax.Array, jax.core.Tracer)):
            if dtype is not None and value.dtype != dtype:
                value = value.astype(dtype)
        else:
            if dtype is None and isinstance(value, (float,)):
                dtype = get_default_dtype()
            if dtype is None and isinstance(value, (list, tuple)):
                arr = np.asarray(value)
                if arr.dtype == np.float64:
                    dtype = get_default_dtype()
            value = jnp.asarray(value, dtype=dtype)
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        self.is_distributed = False
        self._has_producer = False
        self._retain_grad = False
        # per-VALUE tape identity: refreshed by in-place mutation so autograd
        # routes cotangents to the right version (the reference's
        # TensorInplaceVersion counter, `framework/tensor.h:77`)
        self._key = object()

    # ---- metadata -------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def rank(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        try:
            dev = next(iter(self._value.devices()))
            return f"Place({dev.platform}:{dev.id})"
        except Exception:
            return "Place(traced)"

    @property
    def is_leaf(self):
        return not self._has_producer

    @property
    def T(self):
        # paddle.Tensor.T reverses all dims
        perm = tuple(range(self._value.ndim - 1, -1, -1))
        return apply(lambda v: jnp.transpose(v, perm), self)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._value.shape[0]

    def __repr__(self):
        try:
            data = np.asarray(self._value)
            body = np.array2string(data, precision=6, separator=", ")
        except Exception:
            body = f"<traced {self._value.aval}>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                f"stop_gradient={self.stop_gradient},\n       {body})")

    # ---- host interchange ----------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        return np.asarray(self._value).item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __float__(self):
        return float(np.asarray(self._value))

    def __int__(self):
        return int(np.asarray(self._value))

    def __bool__(self):
        return bool(np.asarray(self._value))

    def __index__(self):
        return int(np.asarray(self._value))

    # ---- autograd -------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def gradient(self):
        return None if self.grad is None else np.asarray(self.grad._value)

    def retain_grads(self):
        self._retain_grad = True

    def _accumulate_grad(self, g):
        if self.grad is None:
            self.grad = Tensor(g, stop_gradient=True)
        else:
            self.grad = Tensor(self.grad._value + g, stop_gradient=True)

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self.stop_gradient = True
        self._has_producer = False
        return self

    def stop_gradient_(self, flag=True):
        self.stop_gradient = flag
        return self

    # ---- value mutation (optimizer in-place updates) -------------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value, dtype=self._value.dtype)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._value.shape}")
        self._value = value
        self._key = object()
        return self

    def copy_(self, other):
        return self.set_value(other)

    # ---- device / dtype movement ---------------------------------------
    def cpu(self):
        return Tensor(np.asarray(self._value), stop_gradient=self.stop_gradient)

    def cuda(self, *a, **k):
        return self

    def to(self, *args, **kwargs):
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, (str, np.dtype)) and str(a) not in ("cpu", "gpu", "tpu"):
                try:
                    dtype = convert_dtype(a)
                except ValueError:
                    pass
        if dtype is not None:
            return self.astype(dtype)
        return self

    def pin_memory(self):
        return self

    # ---- indexing -------------------------------------------------------
    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return apply(lambda v: v[idx], self)

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        vt = value if isinstance(value, Tensor) else None
        requires = autograd.grad_enabled() and (
            not self.stop_gradient or (vt is not None and not vt.stop_gradient))
        if not requires:
            if vt is not None:
                value = vt._value
            self._value = self._value.at[idx].set(value)
            self._key = object()
            return self
        if vt is None:
            vt = Tensor(value)
        # recorded scatter: grad w.r.t. the old value is zeroed at idx, grad
        # w.r.t. the assigned value is the cotangent gathered at idx
        return self._inplace_apply(
            lambda v, u: v.at[idx].set(u.astype(v.dtype)), vt)

    def _inplace_apply(self, fn, *others):
        """In-place update self._value = fn(old_value, *other_values), recorded
        on the tape. The node's input key is self's pre-mutation key (earlier
        producers still receive the old value's cotangent); self then gets a
        fresh key as the node's sole output."""
        vals = (self._value,) + tuple(t._value for t in others)
        requires = autograd.grad_enabled() and any(
            not t.stop_gradient for t in (self,) + others)
        if not requires:
            self._value = fn(*vals)
            self._key = object()
            return self
        new_val, vjp_fn = jax.vjp(fn, *vals)
        node = autograd.Node((self,) + others, (self,), vjp_fn, False)
        self._key = object()          # post-mutation value identity
        node.out_keys = (self._key,)
        autograd.record(node)
        self._value = new_val
        self.stop_gradient = False
        return self

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    # generic method plumbing: ops attach themselves via register_method
    def __getattr__(self, item):
        fn = _tensor_method_registry.get(item)
        if fn is None:
            raise AttributeError(f"'Tensor' object has no attribute {item!r}")
        return fn.__get__(self, Tensor)


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(i._value if isinstance(i, Tensor) else i for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(np.asarray(idx))
    return idx


class Parameter(Tensor):
    """Trainable tensor — analog of `framework.py:5954` ParamBase."""

    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.trainable = trainable
        self.persistable = True

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


# ---------------------------------------------------------------------------
# eager dispatch
# ---------------------------------------------------------------------------

# static-graph capture: when a recorder is pushed (paddle_tpu.static
# program_guard), every apply() also logs a replayable forward op — the
# ProgramDesc analog (reference `framework.proto:225`)
_capture_stack = []


def push_capture(recorder):
    _capture_stack.append(recorder)


def pop_capture():
    return _capture_stack.pop()


def active_capture():
    return _capture_stack[-1] if _capture_stack else None


def apply(fn, *tensors):
    """Run `fn` over the raw values of `tensors`; record vjp on the tape when
    gradient is required. fn takes/returns jax values (single or tuple)."""
    vals = tuple(t._value for t in tensors)
    requires = autograd.grad_enabled() and any(
        not t.stop_gradient for t in tensors)
    if requires:
        outs, vjp_fn = jax.vjp(fn, *vals)
    else:
        outs = fn(*vals)
    multi = isinstance(outs, (tuple, list))
    out_list = list(outs) if multi else [outs]
    if _debug_flags_on():
        _debug_check(fn, out_list)
    wrapped = [Tensor(o, stop_gradient=not requires) for o in out_list]
    if requires:
        autograd.record(autograd.Node(tensors, tuple(wrapped), vjp_fn, multi))
    if _capture_stack:
        _capture_stack[-1].record_op(fn, tensors, tuple(wrapped), multi)
    return wrapped if multi else wrapped[0]


def _debug_flags_on():
    from .. import flags
    return flags.get_flag("check_nan_inf") or flags.get_flag("benchmark")


def _debug_check(fn, out_list):
    """Per-op debug hooks, gated on runtime flags (both force host sync on
    concrete values — that is the point of the modes). Analog of the
    reference's FLAGS_check_nan_inf op-output scan
    (`framework/details/nan_inf_utils_detail.cc:1`) and FLAGS_benchmark."""
    from .. import flags
    for o in out_list:
        if isinstance(o, jax.core.Tracer):
            continue  # under jit tracing: TrainStep owns the compiled check
        if flags.get_flag("benchmark") and isinstance(o, jax.Array):
            o.block_until_ready()
        if (flags.get_flag("check_nan_inf") and isinstance(o, jax.Array)
                and jnp.issubdtype(o.dtype, jnp.floating)):
            if not bool(jnp.isfinite(o).all()):
                op = getattr(fn, "__qualname__", None) or repr(fn)
                msg = (f"check_nan_inf: op {op} produced a non-finite "
                       f"output (shape={tuple(o.shape)}, dtype={o.dtype})")
                if flags.get_flag("check_nan_inf_level") >= 1:
                    import warnings
                    warnings.warn(msg)
                else:
                    raise FloatingPointError(msg)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor analog (`python/paddle/tensor/creation.py`)."""
    if isinstance(data, Tensor):
        t = Tensor(data._value, dtype=dtype, stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def as_tensor_args(*args, dtype=None):
    return tuple(a if isinstance(a, Tensor) else Tensor(a, dtype=dtype)
                 for a in args)


def register_method(name, fn=None):
    """Attach a function as a Tensor method (the reference monkey-patches
    VarBase the same way, `varbase_patch_methods.py:monkey_patch_varbase`)."""
    if fn is None:
        def deco(f):
            _tensor_method_registry[name] = f
            return f
        return deco
    _tensor_method_registry[name] = fn
    return fn
