"""Cross-layout checkpoint resharding: elastic resume across mesh changes.

PR 5's resilience runtime resumes bit-identically — onto the SAME
layout. On a preemptible fleet that is half the problem: losing a host
invalidates the ICI mesh, the elastic relaunch lands on a different
chip count, and the planner (`paddle_tpu.planner.plan`) hands the
survivor a different dp/fsdp/tp/pp factorization. This module carries
the training state across that layout change (the Pathways-style
resharded resume; reference lineage: the fleet elastic manager's
checkpoint-restart protocol, `fleet/elastic/manager.py`):

- `reshard_restore(ckpt_dir, step, target_layout, mesh)` loads a PR-5
  manifest checkpoint saved under layout A into a model living under
  ANY planner layout B — smaller or larger world, different axes —
  leaf by leaf with the TARGET `Sharding` attached to each restore
  (orbax reads only the shards each host needs: no full-model host
  materialization on any single host), covering optimizer slots and
  the `core/random` RNG key exactly like a same-layout resume;
- the manifest is cross-checked first (per-leaf shape/dtype, per-file
  sha256), a corrupt file is still reported as a corrupt LEAF, and
  `step=None` keeps `CheckpointManager.restore`'s newest -> oldest
  fallback semantics (an explicit step raises instead);
- checkpoints record the layout they were saved under
  (`RunState.layout`), so `ResilienceManager.resume()` can route
  through this module automatically when the stored layout mismatches
  the live one — the relaunched process never needs to know whether
  the world changed.

The restore deliberately places parameters on their TAG-derived
shardings (`env.param_sharding`); ZeRO re-placement (stage-3 dp
sharding of params/states) stays where it always happened — in
`ShardedTrainStep.__init__` — so the reshard path has exactly one
placement rule instead of a second copy of the trainer's.
"""
import os
import warnings

import numpy as np

from .. import monitor
from .ckpt import (CheckpointError, CheckpointManager, load_manifest)

__all__ = ["reshard_restore", "normalize_layout", "layout_from_mesh",
           "layouts_differ", "stored_layout"]

MESH_AXES = ("dp", "pp", "mp", "sp", "ep")


# ---------------------------------------------------------------------------
# layout identity
# ---------------------------------------------------------------------------

def normalize_layout(layout):
    """Canonical layout dict from a planner `Layout`, a dict, or None.

    The canonical form carries every mesh axis (missing axes are 1) and
    `zero_stage` when the source declares one — enough to decide
    whether two runs share a placement, nothing more."""
    if layout is None:
        return None
    if hasattr(layout, "to_dict"):          # planner.Layout
        layout = layout.to_dict()
    if not isinstance(layout, dict):
        raise TypeError(
            f"layout must be a planner Layout or an axis dict, got "
            f"{type(layout).__name__}")
    out = {}
    for a in MESH_AXES:
        v = int(layout.get(a, 1))
        if v < 1:
            raise ValueError(f"layout axis {a} size {v} < 1")
        out[a] = v
    if layout.get("zero_stage") is not None:
        out["zero_stage"] = int(layout["zero_stage"])
    return out


def layout_from_mesh(mesh):
    """The live mesh's layout dict (axes absent from the mesh are 1)."""
    if mesh is None:
        return None
    out = {}
    for a in MESH_AXES:
        out[a] = int(mesh.shape[a]) if a in mesh.axis_names else 1
    return out


def layouts_differ(a, b):
    """Do two layouts place state differently? Mesh axes always count;
    zero_stage counts only when BOTH sides declare one (a mesh-derived
    layout carries no stage and must not spuriously mismatch)."""
    a, b = normalize_layout(a), normalize_layout(b)
    if a is None or b is None:
        return False
    if any(a[ax] != b[ax] for ax in MESH_AXES):
        return True
    if "zero_stage" in a and "zero_stage" in b and \
            a["zero_stage"] != b["zero_stage"]:
        return True
    return False


def stored_layout(manager, step=None):
    """The layout stamped into a committed checkpoint's RunState (the
    newest committed step by default), or None when no checkpoint —
    or no stamp (a pre-elastic checkpoint) — exists. Reads only
    run_state.json; integrity verification happens at restore time."""
    import json
    from .ckpt import RUN_STATE_NAME
    if step is None:
        step = manager.latest_step()
    if step is None:
        return None
    path = os.path.join(manager.step_dir(step), RUN_STATE_NAME)
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    layout = d.get("layout")
    return normalize_layout(layout) if layout else None


# ---------------------------------------------------------------------------
# the resharding leaf loader
# ---------------------------------------------------------------------------

def _flat_leaves(tree, prefix=""):
    """Dotted-name -> live leaf for a `_state_pytree` tree, joining
    keys exactly like `ckpt.flatten_leaves` so names line up with the
    manifest's leaf table."""
    out = {}
    for k, v in tree.items():
        name = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flat_leaves(v, prefix=name + "."))
        else:
            out[name] = v
    return out


def _restore_structure(ckptr, path, saved):
    """The checkpoint's own tree structure, each leaf holding its
    dotted name. Primary source: orbax `metadata()` — it preserves
    EMPTY subtrees (a stateless-SGD run saves `"optimizer": {}`, and
    a restore_args tree missing that key is a structure mismatch
    orbax rejects outright). Fallback: reconstruction from the
    manifest's leaf names (which cannot represent empty subtrees but
    keeps a metadata-less checkpoint restorable)."""
    try:
        md = ckptr.metadata(path)

        def walk(sub, prefix=""):
            out = {}
            for k, v in sub.items():
                if isinstance(v, dict):
                    out[k] = walk(v, f"{prefix}{k}.")
                else:
                    out[k] = getattr(v, "name", None) or f"{prefix}{k}"
            return out

        if isinstance(md, dict):
            return walk(md)
    except Exception:
        pass
    return _unflatten_state_leaves(saved.keys())


def _unflatten_state_leaves(names):
    """Rebuild the `_state_pytree` nesting from dotted manifest names.

    The nesting is known by construction — {"model": {state_dict_key},
    "optimizer": {param_name: {slot}}} — which is what makes the
    dotted names (whose components themselves contain dots)
    unambiguous: a model leaf's key is everything after "model.", an
    optimizer leaf splits on the LAST dot into (param, slot)."""
    tree = {}
    for name in names:
        if name.startswith("model."):
            tree.setdefault("model", {})[name[len("model."):]] = name
        elif name.startswith("optimizer."):
            rest = name[len("optimizer."):]
            if "." not in rest:
                raise CheckpointError(
                    f"manifest optimizer leaf {name!r} has no slot "
                    "component")
            param, slot = rest.rsplit(".", 1)
            tree.setdefault("optimizer", {}).setdefault(param, {})[slot] \
                = name
        else:
            raise CheckpointError(
                f"manifest leaf {name!r} is outside the model/optimizer "
                "state tree — not a resilience-protocol checkpoint")
    return tree


def _target_shardings(model, optimizer, mesh):
    """Dotted leaf name -> target Sharding under the live mesh.

    Model leaves take their TAG-derived placement (`env.param_sharding`
    over the tensor's mesh_axes — the same single rule `shard_model`
    applies). Optimizer slots follow their parameter's placement when
    they are parameter-shaped (moments, velocity, master copies) and
    replicate otherwise (beta-power scalars). Empty with no mesh
    (plain single-device restore)."""
    from ..distributed import env as dist_env
    if mesh is None:
        return {}
    out = {}
    for k, t in model.state_dict().items():
        out[f"model.{k}"] = dist_env.param_sharding(t, mesh)
    if optimizer is not None:
        for pname, p in model.named_parameters():
            st = optimizer._states.get(id(p)) or {}
            psh = dist_env.param_sharding(p, mesh)
            pshape = tuple(p._value.shape)
            for slot, v in st.items():
                vshape = tuple(getattr(v, "shape", ()))
                out[f"optimizer.{pname}.{slot}"] = \
                    psh if vshape == pshape else dist_env.replicated(mesh)
    return out


def _load_resharded(path, model, optimizer, mesh):
    """The loader `CheckpointManager.restore(loader=...)` dispatches to:
    restore `path` (a step's arrays dir) into the live model/optimizer
    with per-leaf TARGET shardings. Shape mismatches raise naming the
    leaf (permanent — the retry layer fails fast on ValueError)."""
    import jax.numpy as jnp
    import orbax.checkpoint as ocp
    from ..distributed.checkpoint import _state_pytree

    step_dir = os.path.dirname(os.path.abspath(path))
    manifest = load_manifest(step_dir)
    saved = manifest.get("leaves") or {}
    if not saved:
        raise CheckpointError(
            f"{step_dir}: manifest carries no leaf table — cannot "
            "cross-check a reshard against it")

    # prime lazily-created optimizer slots so the checkpoint's
    # optimizer leaves find their in-memory targets (a fresh relaunch
    # has never run a step, so _states is empty until now)
    params = {k: p for k, p in model.named_parameters()}
    if optimizer is not None:
        for p in params.values():
            optimizer._get_state(p)
    target = _state_pytree(model, optimizer)
    live = _flat_leaves(target)

    shardings = _target_shardings(model, optimizer, mesh)

    # per-leaf manifest cross-check: every model leaf the live model
    # needs must exist with the same LOGICAL shape (layouts change
    # placement, never logical shape); dtype differences are cast at
    # restore like a same-layout resume
    missing = [n for n in live
               if n.startswith("model.") and n not in saved]
    if missing:
        raise CheckpointError(
            f"checkpoint at {step_dir} lacks model leaves the live "
            f"model requires: {missing[:4]}"
            + (f" (+{len(missing) - 4} more)" if len(missing) > 4 else ""))
    for name, meta in saved.items():
        v = live.get(name)
        if v is None:
            continue
        want = tuple(int(s) for s in meta.get("shape", ()))
        have = tuple(getattr(getattr(v, "_value", v), "shape", ()))
        if want != have:
            raise ValueError(
                f"reshard shape mismatch for leaf {name}: checkpoint "
                f"{want} vs live model {have} — a layout change moves "
                "shards, it never changes logical shapes")

    # restore args mirror the CHECKPOINT's tree (orbax requires the
    # exact structure), each matched leaf carrying its target Sharding
    # so every host reads only the shards it owns; leaves the live
    # process no longer wants (e.g. restoring without the optimizer)
    # degrade to host numpy and are dropped at write-back
    orphans = []
    ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler(use_ocdbt=False))
    structure = _restore_structure(ckptr, path, saved)

    def _args(sub):
        out = {}
        for k, v in sub.items():
            if isinstance(v, dict):
                out[k] = _args(v)
                continue
            name = v
            tgt = live.get(name)
            if tgt is None:
                orphans.append(name)
                out[k] = ocp.RestoreArgs()
                continue
            arr = getattr(tgt, "_value", tgt)
            sh = shardings.get(name)
            if sh is None:
                # no mesh: plain host restore (single-device relaunch)
                out[k] = ocp.RestoreArgs(restore_type=np.ndarray)
            else:
                out[k] = ocp.ArrayRestoreArgs(
                    sharding=sh, global_shape=tuple(arr.shape),
                    dtype=np.dtype(arr.dtype))
        return out

    restore_args = _args(structure)
    if orphans:
        warnings.warn(
            f"reshard: {len(orphans)} checkpoint leaves have no live "
            f"target and were dropped (first: {orphans[0]})",
            RuntimeWarning, stacklevel=3)
    restored = ckptr.restore(
        path, args=ocp.args.PyTreeRestore(restore_args=restore_args))

    # write back in place: model leaves onto their tensors (cast to
    # the live dtype — ArrayRestoreArgs already did, this is belt and
    # suspenders for the no-mesh numpy path), optimizer leaves onto
    # their slots
    sd = model.state_dict()
    for k, t in sd.items():
        if k in restored.get("model", {}):
            v = restored["model"][k]
            if not hasattr(v, "sharding"):
                v = jnp.asarray(v)
            t._value = v.astype(t._value.dtype) \
                if v.dtype != t._value.dtype else v
    if optimizer is not None:
        for pname, slots in restored.get("optimizer", {}).items():
            p = params.get(pname)
            if p is None:
                continue
            cur = optimizer._get_state(p)
            for sk, v in slots.items():
                if sk not in cur:
                    continue
                if not hasattr(v, "sharding"):
                    v = jnp.asarray(v)
                cur[sk] = v
    return restored


# ---------------------------------------------------------------------------
# the public entry
# ---------------------------------------------------------------------------

def reshard_restore(ckpt_dir, step=None, target_layout=None, mesh=None,
                    model=None, optimizer=None, manager=None, rank=0,
                    sink=None, retry=None):
    """Restore a PR-5 manifest checkpoint saved under ANY layout into
    the live model under `target_layout`. Returns the checkpoint's
    RunState (RNG re-seeded), or None when no checkpoint exists.

    ckpt_dir       CheckpointManager root (step_N subdirectories)
    step           exact step (corruption raises) or None for the
                   newest VALID checkpoint with the standard
                   newest -> oldest fallback past corrupt ones
    target_layout  planner Layout / axis dict the live process runs
                   under (defaults to the live mesh's layout)
    mesh           the live jax Mesh (defaults to the process mesh);
                   None restores plain single-device arrays
    manager        reuse an existing CheckpointManager (its retries,
                   sink and telemetry identity) instead of building one

    Every restore emits the usual `kind=ckpt` restore/fallback records
    plus one `kind=elastic` reshard_restore record referencing the
    committed step and BOTH layouts (tools/trace_check.py enforces
    that shape), and advances `elastic.reshard_restores`.
    """
    from ..distributed import env as dist_env
    if mesh is None:
        mesh = dist_env.current_mesh()
    target_layout = normalize_layout(target_layout) \
        if target_layout is not None else layout_from_mesh(mesh)
    mgr = manager
    owns = mgr is None
    if owns:
        mgr = CheckpointManager(ckpt_dir, model=model, optimizer=optimizer,
                                retry=retry, rank=rank, sink=sink)
    model = model if model is not None else mgr.model
    optimizer = optimizer if optimizer is not None else mgr.optimizer

    def _loader(path, model_, optimizer_):
        return _load_resharded(path, model_, optimizer_, mesh)

    try:
        rs = mgr.restore(step=step, model=model, optimizer=optimizer,
                         loader=_loader)
    finally:
        if owns:
            mgr.close()
    if rs is None:
        return None
    monitor.incr("elastic.reshard_restores")
    from ..telemetry.sink import emit_record, make_elastic_record
    rec = make_elastic_record(
        "reshard_restore", rank=rank, step=rs.step,
        layout_from=rs.layout or {"unknown": 1},
        layout_to=target_layout or {"unknown": 1})
    emit_record(rec, sink, mgr.sink if not owns else None)
    return rs
