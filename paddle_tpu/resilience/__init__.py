"""paddle_tpu.resilience — the fault-tolerance runtime.

PRs 1–4 built the eyes (flight recorder, graph doctor, health monitor +
watchdog, compile observatory); this subsystem is the hands: a training
job that SURVIVES what those eyes see. Reference lineage: the HDFS
auto-checkpoint subsystem (`fluid/incubate/checkpoint/auto_checkpoint.py`)
and the elastic fleet relaunch protocol, rebuilt step-granular and
integrity-checked for the single-controller TPU regime.

Five pillars:

- `ckpt`    — CheckpointManager: atomic step checkpoints (tmp-dir +
              manifest with per-leaf digests + fsync + one rename),
              keep-last-K/keep-every-N retention, at-most-one async
              save in flight, restore that verifies integrity and
              falls back past corrupt checkpoints; RunState for
              bit-identical step-granular resume (incl. RNG).
- `retry`   — with_retry/RetryPolicy: exponential backoff + full
              jitter, deadlines, shared retry budgets, transient-vs-
              permanent classification. Also used by distributed/fs.py.
- `preempt` — PreemptionHandler (SIGTERM -> checkpoint-at-next-step-
              boundary) + ResilienceManager, the `resilience=` hook on
              TrainStep/ShardedTrainStep/PipelineParallel; graceful
              exit with RESUMABLE_EXIT_CODE and auto-resume.
- `chaos`   — seeded fault injection (transient I/O errors, slow
              writes, corrupt-a-shard-after-write); the in-process half
              of `tools/chaos_drill.py`.
- `reshard` — cross-layout checkpoint resharding: restore a manifest
              checkpoint saved under layout A into any planner layout
              B (elastic shrink/grow), leaf-by-leaf with target
              Shardings; `resume()` routes through it automatically
              when the stored layout mismatches the live one (the
              `distributed.elastic.ElasticCoordinator` relaunch path;
              drilled by `tools/elastic_drill.py`).

`ckpt.*` counters/gauges land on the PR-3 `/metrics` endpoint; every
checkpoint event is a `kind=ckpt` JSONL record validated by
`tools/trace_check.py` and judged by the health AnomalyDetector's
`checkpoint_stall`/`checkpoint_failed` rules.
"""
from . import chaos  # noqa: F401
from . import ckpt  # noqa: F401
from . import preempt  # noqa: F401
from . import reshard  # noqa: F401
from . import retry  # noqa: F401
from .chaos import ChaosConfig, ChaosMonkey, corrupt_one_file  # noqa: F401
from .ckpt import (  # noqa: F401
    CheckpointCorruptError, CheckpointError, CheckpointManager, RunState,
    build_manifest, checkpoint_bytes, load_manifest, verify_checkpoint)
from .preempt import (  # noqa: F401
    RESUMABLE_EXIT_CODE, PreemptionHandler, ResilienceManager,
    as_resilience)
from .reshard import (  # noqa: F401
    layout_from_mesh, layouts_differ, normalize_layout, reshard_restore,
    stored_layout)
from .retry import (  # noqa: F401
    RetryBudget, RetryError, RetryPolicy, classify_failure, is_transient,
    retrying, tag_transient, with_retry)

__all__ = [
    "CheckpointManager", "RunState", "CheckpointError",
    "CheckpointCorruptError", "build_manifest", "load_manifest",
    "verify_checkpoint", "checkpoint_bytes",
    "RetryPolicy", "RetryBudget", "RetryError", "with_retry", "retrying",
    "is_transient", "classify_failure", "tag_transient",
    "RESUMABLE_EXIT_CODE", "PreemptionHandler", "ResilienceManager",
    "as_resilience",
    "reshard_restore", "normalize_layout", "layout_from_mesh",
    "layouts_differ", "stored_layout",
    "ChaosConfig", "ChaosMonkey", "corrupt_one_file",
    "ckpt", "retry", "preempt", "chaos", "reshard",
]
