"""Preemption-aware graceful shutdown + the `resilience=` train hook.

A TPU pod job does not end with an exception: it ends with a SIGTERM
from the scheduler (maintenance event, spot reclaim, elastic rescale)
and a grace window measured in seconds. The reference framework's
answer was the elastic manager's relaunch protocol plus HDFS
auto-checkpoint; this module is the step-granular TPU-build version:

- `PreemptionHandler` turns SIGTERM/SIGINT into an ARMED FLAG, not an
  exception — a signal mid-XLA-dispatch must not unwind the stack
  through a donated-buffer update;
- train steps wired with `resilience=` (TrainStep / ShardedTrainStep /
  `PipelineParallel.resilience`, the same pattern as `health=`/`lint=`)
  call `ResilienceManager.step_boundary()` between steps; an armed
  request there drains the in-flight async save, commits a final
  checkpoint synchronously, writes a black-box dump through the
  watchdog machinery, and exits with `RESUMABLE_EXIT_CODE` — a code
  the launcher can distinguish from a crash (restart-and-resume) and
  from `ELASTIC_EXIT_CODE` (restart-with-new-world);
- `RunState` (saved inside every checkpoint) carries step, epoch,
  data position and `core/random` RNG state, so `resume()` restarts
  bit-identical at STEP granularity, not epoch.
"""
import os
import signal
import threading
import time
import warnings

from .. import monitor
from .ckpt import CheckpointManager, RunState

__all__ = ["RESUMABLE_EXIT_CODE", "PreemptionHandler", "ResilienceManager",
           "as_resilience"]

# exit-code protocol: 101 (ELASTIC_EXIT_CODE) = relaunch with a new
# world; 102 = graceful preemption exit, state committed, relaunch and
# auto-resume from the checkpoint. Distinct so the launcher/driver can
# tell "resume me" from "rebuild me" from a real crash.
RESUMABLE_EXIT_CODE = 102


class PreemptionHandler:
    """Arm a 'checkpoint at the next step boundary' request on SIGTERM.

    handler = PreemptionHandler().install()
    ...
    if handler.requested: ...            # polled between steps

    The signal handler only sets a flag (async-signal-safe by
    construction); all real work happens at the next step boundary on
    the main thread. `request()` arms it programmatically (tests,
    chaos drills, cooperative shutdown). install()/uninstall() save and
    restore the previous handlers; install from a non-main thread is a
    warning no-op (the boundary check then relies on `request()`).
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._prev = {}
        self._requested = None     # (signal number or None, monotonic ts)
        self.installed = False

    def _on_signal(self, signum, frame):
        self._requested = (signum, time.monotonic())
        monitor.incr("ckpt.preempt_signals")

    def install(self):
        if self.installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            warnings.warn(
                "PreemptionHandler.install() outside the main thread: "
                "signal handlers cannot be set; only request() will arm",
                RuntimeWarning, stacklevel=2)
            return self
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        self.installed = True
        return self

    def uninstall(self):
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):     # non-main thread / teardown
                pass
        self._prev.clear()
        self.installed = False

    def request(self, signum=None):
        """Arm the shutdown request without a real signal."""
        self._requested = (signum, time.monotonic())
        return self

    @property
    def requested(self):
        return self._requested is not None

    @property
    def signal_name(self):
        if self._requested is None:
            return None
        signum = self._requested[0]
        if signum is None:
            return "request()"
        try:
            return signal.Signals(signum).name
        except ValueError:
            return str(signum)


class ResilienceManager:
    """The `resilience=` hook: periodic step checkpoints + preemption-
    aware graceful shutdown + auto-resume.

        res = ResilienceManager("/ckpts/job", save_every=100,
                                hang-free defaults elsewhere)
        step = TrainStep(model, loss_fn, opt, resilience=res)
        start = res.resume() or 0        # restores model/opt/RNG if a
                                         # committed checkpoint exists
        for i in range(start, total_steps):
            loss = step(*batch_at(i))    # step_boundary runs after
                                         # each completed step

    On SIGTERM the NEXT step boundary drains the in-flight save,
    commits a synchronous final checkpoint, dumps a black box (the
    PR-3 watchdog format), and raises SystemExit(RESUMABLE_EXIT_CODE).

    save_every=0 disables periodic saves (preemption saves still
    happen). The underlying CheckpointManager can be shared/preset via
    `manager=`; otherwise one is built over `checkpoint_dir`.
    """

    def __init__(self, checkpoint_dir=None, manager=None, model=None,
                 optimizer=None, save_every=100, keep_last=3,
                 keep_every=None, async_save=True, retry=None,
                 preempt=True, exit_on_preempt=True,
                 exit_code=RESUMABLE_EXIT_CODE, dump_dir=None, health=None,
                 sink=None, rank=0, layout=None, elastic=None):
        if (checkpoint_dir is None) == (manager is None):
            raise ValueError("ResilienceManager: pass exactly one of "
                             "checkpoint_dir or manager")
        self.ckpt = manager if manager is not None else CheckpointManager(
            checkpoint_dir, model=model, optimizer=optimizer,
            keep_last=keep_last, keep_every=keep_every,
            async_save=async_save, retry=retry, rank=rank, health=health,
            sink=sink)
        self.save_every = int(save_every)
        self.exit_on_preempt = bool(exit_on_preempt)
        self.exit_code = int(exit_code)
        self.dump_dir = dump_dir if dump_dir is not None else self.ckpt.dir
        # the layout this run trains under: stamped into every
        # checkpoint's RunState so a relaunch onto a DIFFERENT layout
        # is detected and resume() routes through the reshard path
        # (planner Layout / axis dict / None — see resilience.reshard)
        from .reshard import normalize_layout
        self.layout = normalize_layout(layout)
        # optional distributed.elastic.ElasticCoordinator: polled at
        # every step boundary (heartbeat + failure detection + the
        # shrink/grow replan-drain-relaunch protocol). Wiring must be
        # TWO-way — the coordinator drains its final checkpoint through
        # us — so route through its attach() (which also shares our
        # telemetry sink) rather than a bare assignment.
        self.elastic = None
        if elastic is not None:
            if hasattr(elastic, "attach"):
                elastic.attach(self)     # sets self.elastic = elastic
            else:
                self.elastic = elastic
        self.state = RunState(layout=self.layout)
        self.resumed_from = None
        self.resumed_via = None
        self._shutdown_done = False
        if isinstance(preempt, PreemptionHandler):
            self.handler = preempt.install()
        elif preempt:
            self.handler = PreemptionHandler().install()
        else:
            self.handler = None

    # -- train-step wiring --------------------------------------------------
    def attach(self, model, optimizer=None):
        """Late-bind the model/optimizer (the train step passes its own
        when the manager was built from a bare directory)."""
        if self.ckpt.model is None:
            self.ckpt.model = model
        if self.ckpt.optimizer is None and optimizer is not None:
            self.ckpt.optimizer = optimizer
        return self

    def note(self, epoch=None, data_position=None, **extra):
        """Update run-position fields carried by the next checkpoint."""
        if epoch is not None:
            self.state.epoch = int(epoch)
        if data_position is not None:
            self.state.data_position = data_position
        self.state.extra.update(extra)
        return self

    def step_boundary(self, loss=None):
        """Called by the wired train step after each COMPLETED step.
        Advances the step count; polls the elastic coordinator (which
        may itself drain + exit with ELASTIC_EXIT_CODE on a membership
        change); on an armed preemption request commits a final
        checkpoint and exits resumable; otherwise saves on the
        periodic schedule."""
        self.state.step += 1
        if self.elastic is not None:
            self.elastic.step_boundary(self.state.step)
        if self.handler is not None and self.handler.requested:
            self.graceful_shutdown()
            return
        if self.save_every and self.state.step % self.save_every == 0:
            self.ckpt.save(self.state.step,
                           run_state=self.state.snapshot())

    def graceful_shutdown(self, reason=None, exit_code=None):
        """Drain + final synchronous checkpoint + black-box dump + (by
        default) SystemExit. Idempotent — a second call (signal during
        shutdown) exits without re-saving. `exit_code` overrides the
        configured one for this exit: the elastic coordinator drains
        through here with ELASTIC_EXIT_CODE (relaunch onto a NEW
        world) instead of the default RESUMABLE_EXIT_CODE (resume onto
        the same one)."""
        code = int(exit_code) if exit_code is not None else self.exit_code
        if self._shutdown_done:
            if self.exit_on_preempt:
                raise SystemExit(code)
            return
        self._shutdown_done = True
        sig = self.handler.signal_name if self.handler is not None else None
        reason = reason or (f"preemption ({sig or 'requested'}): graceful "
                            f"shutdown at step {self.state.step}")
        monitor.incr("ckpt.preemptions")
        err = None
        try:
            self.ckpt.save(self.state.step,
                           run_state=self.state.snapshot(), block=True)
        except Exception as e:      # the dump must still happen
            err = e
        from ..telemetry.watchdog import dump_black_box
        dump_black_box(
            reason=reason, dump_dir=self.dump_dir,
            ring=list(self.ckpt.records[-16:]),
            extra={"ckpt_step": self.state.step,
                   "ckpt_dir": self.ckpt.dir,
                   "exit_code": code if self.exit_on_preempt else None,
                   "final_save_error": repr(err) if err else None})
        fields = {"signal": sig} if sig else {}
        self.ckpt._emit("preempt", self.state.step, **fields)
        self.close(uninstall=True)
        if err is not None:
            raise err
        if self.exit_on_preempt:
            raise SystemExit(code)

    # -- resume -------------------------------------------------------------
    def resume(self, model=None, optimizer=None, mesh=None):
        """Auto-resume: restore the newest valid checkpoint (if any)
        into the attached model/optimizer + the RNG, adopt its
        RunState, and return the step to continue FROM (== completed
        steps), or None when starting fresh.

        When the newest checkpoint was saved under a DIFFERENT layout
        than this run's (`layout=` at construction, or the live mesh's
        when none was given) — an elastic relaunch landed on a new
        world — the restore routes through the cross-layout reshard
        path (`resilience.reshard.reshard_restore`) instead of the
        same-layout one; `resumed_via` records which ("reshard" /
        "direct")."""
        if model is not None or optimizer is not None:
            self.attach(model, optimizer)
        from . import reshard
        from ..distributed import env as dist_env
        if mesh is None:
            mesh = dist_env.current_mesh()
        live = self.layout or reshard.layout_from_mesh(mesh)
        stored = reshard.stored_layout(self.ckpt)
        if stored is not None and live is not None and \
                reshard.layouts_differ(stored, live):
            rs = reshard.reshard_restore(
                self.ckpt.dir, target_layout=live, mesh=mesh,
                model=self.ckpt.model, optimizer=self.ckpt.optimizer,
                manager=self.ckpt, rank=self.ckpt.rank)
            self.resumed_via = "reshard"
        else:
            rs = self.ckpt.restore()
            self.resumed_via = "direct" if rs is not None else None
        if rs is None:
            return None
        self.state = rs
        # future checkpoints are stamped with the LIVE layout — the
        # reshard already happened, the next resume is same-layout
        self.state.layout = live or rs.layout
        self.resumed_from = rs.step
        monitor.set_gauge("ckpt.resumed_step", float(rs.step))
        return rs.step

    # -- lifecycle ----------------------------------------------------------
    def close(self, uninstall=True):
        try:
            self.ckpt.close()
        finally:
            if uninstall and self.handler is not None:
                self.handler.uninstall()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def as_resilience(arg):
    """Normalize the `resilience=` argument of TrainStep /
    ShardedTrainStep / PipelineParallel: None/False -> None,
    ResilienceManager -> itself (shared across steps), CheckpointManager
    -> wrapped, str -> manager over that directory, dict -> kwargs."""
    if arg is None or arg is False:
        return None
    if isinstance(arg, ResilienceManager):
        return arg
    if isinstance(arg, CheckpointManager):
        return ResilienceManager(manager=arg)
    if isinstance(arg, str):
        return ResilienceManager(checkpoint_dir=arg)
    if isinstance(arg, dict):
        return ResilienceManager(**arg)
    raise TypeError(
        "resilience= expects a ResilienceManager, CheckpointManager, "
        f"checkpoint-dir string, or kwargs dict; got {type(arg).__name__}")
