"""Deterministic fault injection for resilience drills.

You don't know your checkpoint path survives a mid-save crash until
something has crashed mid-save ON PURPOSE. This module is the
in-process half of the chaos harness (`tools/chaos_drill.py` drives the
out-of-process half: SIGKILL at step N via a subprocess driver):

- **transient I/O errors** with a configured probability at named
  injection points (`save`, `commit`, `restore`, `fs`) — raised as
  OSError(EIO) tagged `.transient = True`, so the retry layer
  (`resilience.retry`) treats them exactly like a real storage blip;
- **slow writes** — a configured stall at the same points, for
  exercising the `checkpoint_stall` anomaly rule and save-time budgets;
- **corrupt-a-shard-after-write** — flip bytes in one file of a
  committed checkpoint, which the manifest digest verification must
  catch on restore.

Everything is seeded: the same ChaosConfig produces the same fault
schedule, so a drill that fails replays identically. Injection is
context-scoped (`with ChaosMonkey(cfg).active():`) — nothing in the
hot path pays more than a truthiness check when no monkey is active.
"""
import contextlib
import errno
import os
import random

__all__ = ["ChaosConfig", "ChaosMonkey", "current", "inject",
           "corrupt_one_file"]

_ACTIVE = []     # innermost-last stack of active monkeys


class ChaosConfig:
    """Knobs for one chaos run.

    seed            RNG seed — same seed, same fault schedule
    io_error_rate   P(injected transient OSError) per injection point hit
    slow_write_s    stall injected at save/commit points (0: off)
    ops             injection points that may fault (default all)
    max_faults      hard cap on injected faults (None: unlimited) — a
                    drill can guarantee forward progress
    """

    def __init__(self, seed=0, io_error_rate=0.0, slow_write_s=0.0,
                 ops=("save", "commit", "restore", "fs"), max_faults=None):
        self.seed = int(seed)
        self.io_error_rate = float(io_error_rate)
        self.slow_write_s = float(slow_write_s)
        self.ops = tuple(ops)
        self.max_faults = max_faults

    def __repr__(self):
        return (f"ChaosConfig(seed={self.seed}, "
                f"io_error_rate={self.io_error_rate}, ops={self.ops})")


class ChaosError(OSError):
    """Injected transient I/O failure. Subclasses OSError(EIO) so
    un-instrumented except-clauses treat it as real weather; tagged
    `.transient = True` so `retry.is_transient` retries it."""

    transient = True

    def __init__(self, op, n):
        super().__init__(errno.EIO, f"chaos[{op}] injected I/O error #{n}")
        self.op = op


class ChaosMonkey:
    """Seeded fault injector. Activate with `with monkey.active():` —
    every `inject(op)` call inside the context consults it."""

    def __init__(self, config=None, sleep=None):
        self.config = config or ChaosConfig()
        self._rand = random.Random(self.config.seed)
        self._sleep = sleep or __import__("time").sleep
        self.faults = 0           # injected errors
        self.stalls = 0           # injected slow writes

    def _spent(self):
        mf = self.config.max_faults
        return mf is not None and self.faults >= mf

    def visit(self, op):
        """One injection-point hit: maybe stall, maybe raise."""
        c = self.config
        if op not in c.ops:
            return
        if c.slow_write_s > 0 and op in ("save", "commit"):
            self.stalls += 1
            self._sleep(c.slow_write_s)
        if c.io_error_rate > 0 and not self._spent() \
                and self._rand.random() < c.io_error_rate:
            self.faults += 1
            raise ChaosError(op, self.faults)

    @contextlib.contextmanager
    def active(self):
        _ACTIVE.append(self)
        try:
            yield self
        finally:
            _ACTIVE.remove(self)


def current():
    """The innermost active ChaosMonkey, or None."""
    return _ACTIVE[-1] if _ACTIVE else None


def inject(op):
    """Injection point: called by resilience.ckpt (save/commit/restore)
    and distributed.fs at their I/O boundaries. No-op (one list peek)
    when no monkey is active."""
    m = _ACTIVE[-1] if _ACTIVE else None
    if m is not None:
        m.visit(op)


def corrupt_one_file(ckpt_dir, seed=0, skip=("manifest.json",),
                     prefer=None):
    """Corrupt-a-shard-after-write: pick one data file under `ckpt_dir`
    (deterministically, by seed) and flip its bytes in place. Returns
    the corrupted path (manifest verification must subsequently reject
    it), or None when the directory holds no eligible file. `prefer` is
    a path substring that narrows the pick (e.g. a leaf name, so the
    verifier's leaf attribution can be asserted)."""
    rand = random.Random(seed)
    candidates = []
    for root, _, files in os.walk(ckpt_dir):
        for name in sorted(files):
            if name in skip:
                continue
            p = os.path.join(root, name)
            if os.path.getsize(p) > 0:
                candidates.append(p)
    if prefer:
        narrowed = [p for p in candidates if prefer in p]
        candidates = narrowed or candidates
    if not candidates:
        return None
    path = rand.choice(candidates)
    with open(path, "rb") as f:
        data = bytearray(f.read())
    pos = rand.randrange(len(data))
    data[pos] ^= 0xFF
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    return path
