"""Retry/backoff combinator for flaky distributed I/O.

At pod scale, storage hiccups are weather, not bugs: a GCS 503 during a
checkpoint save, an NFS stall during restore, a kvstore coordinator
restarting mid-heartbeat. The reference framework dealt with this ad
hoc (the HDFS client's `sleep_inter` loop in `fleet/utils/fs.py`); this
module centralizes the policy so every I/O path in the resilience
runtime — checkpoint save/restore (`resilience.ckpt`), the HDFS client
(`distributed/fs.py`), chaos drills — retries the same way and reports
retries to the same `ckpt.retries` counter family.

Design points:

- **exponential backoff with FULL jitter** (delay ~ U[0, min(cap,
  base*mult^n)]): the AWS-architecture result that de-synchronizes a
  pod's worth of hosts all retrying the same flaky filestore;
- **deadlines** bound total wall time (a preemption grace window is
  ~30s — a retry loop must not out-sleep it);
- **retry budgets** (`RetryBudget`) cap the *aggregate* retries a
  subsystem spends, so a persistently broken filesystem degrades to
  fail-fast instead of multiplying every call by max_attempts;
- **transient-vs-permanent classification**: FileNotFoundError or a
  shape mismatch must fail NOW — retrying a permanent error just turns
  a clear traceback into a slow one.

Clock and sleep are injectable, so tests pin the whole schedule with a
fake clock (no real sleeping, no flaky timing assertions).
"""
import errno
import functools
import random
import threading
import time

__all__ = ["RetryPolicy", "RetryBudget", "RetryError", "with_retry",
           "retrying", "is_transient", "classify_failure",
           "tag_transient", "classify_http_status", "retry_after_hint",
           "HTTPStatusError", "TRANSIENT_HTTP_STATUSES"]

# errno values worth retrying: transient kernel/FS/network conditions.
# Deliberately NOT here: ENOSPC/EDQUOT (disk full stays full), EACCES/
# EPERM (permissions don't heal), ENOENT (missing stays missing).
_TRANSIENT_ERRNOS = frozenset({
    errno.EIO, errno.EAGAIN, errno.EBUSY, errno.EINTR, errno.ETIMEDOUT,
    errno.ECONNRESET, errno.ECONNREFUSED, errno.ECONNABORTED,
    errno.ENETUNREACH, errno.ENETRESET, errno.EHOSTUNREACH,
    errno.ESTALE,           # NFS handle went stale — a remount heals it
})

_PERMANENT_TYPES = (FileNotFoundError, PermissionError, IsADirectoryError,
                    NotADirectoryError, ValueError, TypeError, KeyError)

# programming errors: bugs in OUR code, not weather. The elastic exit
# path (`distributed.elastic.elastic_run`) must let these fail LOUDLY
# instead of converting them into a relaunch loop that replays the
# same traceback forever at ELASTIC_EXIT_CODE.
_PROGRAMMING_TYPES = (ValueError, TypeError, KeyError, IndexError,
                      AttributeError, AssertionError, NameError,
                      NotImplementedError, ZeroDivisionError,
                      RecursionError, UnboundLocalError)


# HTTP statuses worth retrying — the serving tier's own refusal
# vocabulary (serving/http.py): 429 is an admission shed and 503 a
# drain, both of which ship a Retry-After that IS the backoff hint;
# 504 is a server-side deadline (the request was fine, the moment was
# not). Deliberately NOT here: every other 4xx (the request itself is
# wrong — retrying replays the same rejection), and other 5xx (can't
# prove transient; the three-way classifier calls them 'infra').
TRANSIENT_HTTP_STATUSES = frozenset({429, 503, 504})


def classify_http_status(status):
    """Three-way taxonomy for an HTTP status from a serving replica:
    429/503/504 'transient' (shed / draining / deadline — the fleet
    router retries elsewhere, honoring Retry-After), other 4xx
    'permanent' (the request is malformed; another replica would reject
    it identically), anything else 'infra'."""
    status = int(status)
    if status in TRANSIENT_HTTP_STATUSES:
        return "transient"
    if 400 <= status < 500:
        return "permanent"
    return "infra"


def retry_after_hint(exc):
    """The server's Retry-After hint carried on `exc` (seconds, float),
    or None. `with_retry` uses it as a backoff FLOOR: the server said
    when the queue will have drained — coming back sooner just re-sheds."""
    hint = getattr(exc, "retry_after_s", None)
    if hint is None:
        return None
    try:
        hint = float(hint)
    except (TypeError, ValueError):
        return None
    return hint if hint >= 0 else None


class HTTPStatusError(RuntimeError):
    """A non-2xx reply from a serving replica, classified by status.
    `http_status` drives `classify_failure`; `retry_after_s` (when the
    reply carried a Retry-After header) becomes the backoff base."""

    def __init__(self, message, http_status, retry_after_s=None):
        super().__init__(message)
        self.http_status = int(http_status)
        self.retry_after_s = None if retry_after_s is None \
            else float(retry_after_s)


class RetryError(Exception):
    """All attempts exhausted (or deadline/budget hit). `last` carries
    the final underlying exception; `attempts` how many were made."""

    def __init__(self, message, last=None, attempts=0):
        super().__init__(message)
        self.last = last
        self.attempts = attempts


def is_transient(exc):
    """Default transient-vs-permanent classifier.

    Transient: timeouts, connection errors, OSError with a transient
    errno (EIO/EAGAIN/ESTALE/...), and anything explicitly tagged
    `exc.transient = True` (the chaos monkey tags its injected faults).
    Permanent: missing files, permissions, type/value errors — retrying
    those only delays the real traceback.
    """
    tagged = getattr(exc, "transient", None)
    if tagged is not None:
        return bool(tagged)
    status = getattr(exc, "http_status", None)
    if status is not None:
        return int(status) in TRANSIENT_HTTP_STATUSES
    if isinstance(exc, (TimeoutError, ConnectionError)):
        return True
    if isinstance(exc, _PERMANENT_TYPES):
        return False
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    # subprocess.TimeoutExpired without importing subprocess eagerly
    if type(exc).__name__ == "TimeoutExpired":
        return True
    return False


def tag_transient(exc, transient=True):
    """Stamp the explicit `.transient` tag on an exception and return
    it. The tag OVERRIDES type-based classification in `is_transient` /
    `classify_failure` — it is how the chaos monkey, the collective
    deadline guard, and the serving drill's injected step faults tell
    the retry/restart machinery "this one is weather" (or, with
    transient=False, "fail loudly now")."""
    exc.transient = bool(transient)
    return exc


def classify_failure(exc):
    """Three-way failure taxonomy for the elastic exit-code protocol:

    'transient'  — weather (per `is_transient`): storage blips, peer
                   timeouts, anything tagged `.transient = True` (the
                   collective deadline guard tags its timeouts) —
                   relaunching is the fix;
    'permanent'  — a programming or environment error (ValueError,
                   TypeError, missing file, permissions, an explicit
                   `.transient = False` tag) — relaunching replays the
                   identical traceback, so fail loudly NOW;
    'infra'      — everything else (RuntimeError, XLA runtime errors,
                   a dead-peer collective failure without a tag):
                   can't prove it's a bug, the relaunch protocol gets
                   the benefit of the doubt.
    """
    tagged = getattr(exc, "transient", None)
    if tagged is True:
        return "transient"
    if tagged is False:
        return "permanent"
    status = getattr(exc, "http_status", None)
    if status is not None:
        return classify_http_status(status)
    if is_transient(exc):
        return "transient"
    if isinstance(exc, _PERMANENT_TYPES) or isinstance(exc,
                                                      _PROGRAMMING_TYPES):
        return "permanent"
    return "infra"


class RetryBudget:
    """A shared, thread-safe allowance of retries for a subsystem.

    Every RETRY (not first attempts) spends one token; an empty budget
    makes with_retry fail fast after the first error. This bounds the
    worst case of a persistently broken filesystem: N calls cost
    N + budget attempts total, not N * max_attempts.
    """

    def __init__(self, tokens=64):
        self._mu = threading.Lock()
        self._tokens = int(tokens)
        self.spent = 0

    def take(self):
        with self._mu:
            if self._tokens <= 0:
                return False
            self._tokens -= 1
            self.spent += 1
            return True

    def remaining(self):
        with self._mu:
            return self._tokens


class RetryPolicy:
    """Backoff schedule + limits.

    max_attempts   total tries (1 == no retry)
    base_delay_s   first backoff cap (full jitter draws from [0, cap])
    max_delay_s    backoff cap ceiling
    multiplier     cap growth per retry
    deadline_s     total wall-time bound across attempts (None: unbounded)
    budget         optional RetryBudget shared across calls
    classify       predicate(exc) -> transient? (default `is_transient`)
    jitter         False: deterministic caps (tests); True: full jitter
    """

    def __init__(self, max_attempts=4, base_delay_s=0.5, max_delay_s=30.0,
                 multiplier=2.0, deadline_s=None, budget=None,
                 classify=None, jitter=True, seed=None):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.deadline_s = deadline_s
        self.budget = budget
        self.classify = classify or is_transient
        self.jitter = bool(jitter)
        self._rand = random.Random(seed)

    def delay(self, attempt):
        """Backoff before retry #`attempt` (1-based). Full jitter:
        U[0, cap]; cap = base * multiplier^(attempt-1), clipped."""
        cap = min(self.max_delay_s,
                  self.base_delay_s * (self.multiplier ** (attempt - 1)))
        return self._rand.uniform(0.0, cap) if self.jitter else cap

    def __repr__(self):
        return (f"RetryPolicy(max_attempts={self.max_attempts}, "
                f"base={self.base_delay_s}s, cap={self.max_delay_s}s, "
                f"deadline={self.deadline_s})")


def with_retry(fn, policy=None, on_retry=None, clock=None, sleep=None,
               label=None):
    """Call `fn()` under `policy`; returns fn's value or raises.

    Permanent errors (per policy.classify) raise immediately, untouched.
    Transient errors back off and retry until attempts, deadline, or the
    shared budget run out — then `RetryError` wraps the last one.

    on_retry(attempt, exc, delay_s) fires before each backoff sleep (the
    checkpoint manager advances `ckpt.retries` here). `clock`/`sleep`
    default to time.monotonic/time.sleep and are injectable for tests.
    """
    policy = policy or RetryPolicy()
    clock = clock or time.monotonic
    sleep = sleep or time.sleep
    name = label or getattr(fn, "__name__", "fn")
    t0 = clock()
    last = None
    attempt = 0
    while attempt < policy.max_attempts:
        attempt += 1
        try:
            return fn()
        except Exception as e:
            if not policy.classify(e):
                raise
            last = e
        if attempt >= policy.max_attempts:
            break
        if policy.budget is not None and not policy.budget.take():
            raise RetryError(
                f"{name}: retry budget exhausted after attempt {attempt}: "
                f"{type(last).__name__}: {last}", last=last,
                attempts=attempt)
        delay = policy.delay(attempt)
        # a Retry-After hint on the failure is a backoff FLOOR: the
        # server told us when its queue drains — a jittered draw below
        # that just re-sheds on arrival
        hint = retry_after_hint(last)
        if hint is not None:
            delay = max(delay, hint)
        if policy.deadline_s is not None and \
                (clock() - t0) + delay > policy.deadline_s:
            raise RetryError(
                f"{name}: deadline {policy.deadline_s}s would be exceeded "
                f"after attempt {attempt}: {type(last).__name__}: {last}",
                last=last, attempts=attempt)
        if on_retry is not None:
            on_retry(attempt, last, delay)
        sleep(delay)
    raise RetryError(
        f"{name}: {policy.max_attempts} attempt(s) failed; last: "
        f"{type(last).__name__}: {last}", last=last, attempts=attempt)


def retrying(policy=None, **kwargs):
    """Decorator form: @retrying(RetryPolicy(max_attempts=5))."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*a, **kw):
            return with_retry(lambda: fn(*a, **kw), policy=policy, **kwargs)
        return wrapped
    return deco
