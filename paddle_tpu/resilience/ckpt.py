"""Step-granular checkpointing with an atomic commit protocol.

The reference framework's HDFS auto-checkpoint subsystem
(`fluid/incubate/checkpoint/auto_checkpoint.py`) survived preemptions by
job-keyed checkpoint dirs and a serialized train status; its TPU-build
descendant (`distributed/checkpoint.py` TrainEpochRange) is epoch-
granular and trusts the filesystem. At pod scale neither is enough: a
GPT-3-class run loses real money per replayed epoch, and "trusts the
filesystem" means a crash mid-save leaves a half-written directory the
next boot happily restores. This module is the step-granular, paranoid
version:

**Atomic commit protocol.** A save writes arrays into
`step_N.tmp/arrays/` (orbax, each host its shards), then a
`run_state.json` (step/epoch/data-position/RNG — what bit-identical
resume needs beyond arrays), then a `manifest.json` carrying per-leaf
shapes/dtypes/byte-sizes and a per-file content digest of EVERYTHING
else in the directory. Files and directory are fsync'd, then ONE
`os.replace(step_N.tmp -> step_N)` commits, then the `latest` marker is
atomically updated. A crash anywhere before the rename leaves only a
`.tmp` husk that restore ignores and GC reaps; a crash after it leaves
a fully-verifiable checkpoint.

**Restore-time integrity.** `verify_checkpoint` replays the manifest:
missing, truncated, or digest-mismatched files are reported with the
offending LEAF named (the orbax layout keys each parameter's directory
by its flattened name). `CheckpointManager.restore()` walks newest ->
oldest, skipping invalid checkpoints (counted as `ckpt.fallbacks`)
instead of crashing or silently restoring garbage.

**At most one async save in flight.** One `AsyncCheckpointer` lives for
the manager's lifetime (fixing the per-call checkpointer/thread leak in
`save_checkpoint`); a new save drains (commits) the previous one first.

**Retention.** keep_last-K plus keep-every-N survivors; everything else
— including uncommitted `.tmp` husks from crashed runs — is GC'd after
each commit.

All I/O goes through `resilience.retry.with_retry` (transient storage
errors back off and retry, counted in `ckpt.retries`) and the chaos
injection points (`resilience.chaos.inject`), so the drill harness
exercises exactly the production code path.
"""
import hashlib
import json
import os
import shutil
import time
import warnings

import numpy as np

from .. import monitor
from . import chaos
from .retry import RetryError, RetryPolicy, with_retry

__all__ = ["CheckpointManager", "RunState", "CheckpointError",
           "CheckpointCorruptError", "build_manifest", "load_manifest",
           "verify_checkpoint", "checkpoint_bytes"]

MANIFEST_NAME = "manifest.json"
RUN_STATE_NAME = "run_state.json"
ARRAYS_SUBDIR = "arrays"
LATEST_NAME = "latest"
MANIFEST_SCHEMA = 1
_STEP_PREFIX = "step_"
_TMP_SUFFIX = ".tmp"


class CheckpointError(RuntimeError):
    """A checkpoint operation failed permanently (retries exhausted or a
    non-transient error)."""


class CheckpointCorruptError(CheckpointError):
    """Integrity verification rejected a checkpoint. `problems` lists
    the findings, each naming the offending file (and leaf when the
    file maps to one)."""

    def __init__(self, path, problems):
        self.path = path
        self.problems = list(problems)
        super().__init__(
            f"checkpoint {path} failed integrity verification: "
            + "; ".join(self.problems[:4])
            + (f" (+{len(self.problems) - 4} more)"
               if len(self.problems) > 4 else ""))


# ---------------------------------------------------------------------------
# run state: everything beyond arrays that bit-identical resume needs
# ---------------------------------------------------------------------------

class RunState:
    """Training-position record saved inside every checkpoint.

    step           completed-steps count == next step index to run
    epoch          current epoch
    data_position  opaque loader cursor (sample/batch offset, shard id —
                   whatever the data pipeline needs to seek back)
    rng_state      `core/random` default generator key (captured at save,
                   re-seeded on restore, so post-resume dropout masks /
                   data shuffles replay the uninterrupted run exactly)
    layout         parallelism layout the run was saved under (axis
                   dict, see resilience.reshard.normalize_layout) —
                   what lets resume() detect a mesh change and route
                   through the cross-layout reshard path
    extra          user dict (JSON-serializable)
    """

    def __init__(self, step=0, epoch=0, data_position=None, rng_state=None,
                 extra=None, layout=None):
        self.step = int(step)
        self.epoch = int(epoch)
        self.data_position = data_position
        self.rng_state = rng_state
        self.layout = dict(layout) if layout else None
        self.extra = dict(extra or {})

    def capture_rng(self):
        """Record the live `core/random` generator key."""
        from ..core.random import default_generator
        key = default_generator().get_state()
        self.rng_state = [int(v) for v in np.asarray(key).ravel()]
        return self

    def restore_rng(self):
        """Re-seed the live generator from the captured key (no-op when
        none was captured)."""
        if self.rng_state is None:
            return self
        import jax.numpy as jnp
        from ..core.random import default_generator
        key = jnp.asarray(np.asarray(self.rng_state, dtype=np.uint32))
        default_generator().set_state(key)
        return self

    def snapshot(self):
        """Copy with the CURRENT rng state captured — what a save should
        persist (the live object keeps mutating afterwards)."""
        return RunState(step=self.step, epoch=self.epoch,
                        data_position=self.data_position,
                        extra=dict(self.extra),
                        layout=self.layout).capture_rng()

    def to_dict(self):
        d = {"schema": MANIFEST_SCHEMA, "step": self.step,
             "epoch": self.epoch, "data_position": self.data_position,
             "rng_state": self.rng_state, "extra": self.extra}
        if self.layout:
            d["layout"] = self.layout
        return d

    @classmethod
    def from_dict(cls, d):
        return cls(step=d.get("step", 0), epoch=d.get("epoch", 0),
                   data_position=d.get("data_position"),
                   rng_state=d.get("rng_state"),
                   extra=d.get("extra"),
                   layout=d.get("layout"))

    def __repr__(self):
        return (f"RunState(step={self.step}, epoch={self.epoch}, "
                f"data_position={self.data_position!r})")


# ---------------------------------------------------------------------------
# durability + manifest primitives
# ---------------------------------------------------------------------------

def _fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:          # pragma: no cover - non-POSIX fallback
        return
    try:
        os.fsync(fd)
    except OSError:          # pragma: no cover - some FSes refuse dir fsync
        pass
    finally:
        os.close(fd)


def _atomic_write_json(path, obj):
    """tmp-write + fsync + rename + dir fsync: the file is either absent
    or complete, never half-written."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


def _sha256(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def flatten_leaves(tree, prefix=""):
    """Dotted-path -> array metadata for every leaf of a state pytree —
    the names match the orbax (use_ocdbt=False) on-disk directory names,
    which is what lets a corrupt FILE be reported as a corrupt LEAF."""
    out = {}
    for k, v in tree.items():
        name = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(flatten_leaves(v, prefix=name + "."))
        else:
            arr = np.asarray(v) if not hasattr(v, "dtype") else v
            out[name] = {"shape": [int(s) for s in getattr(arr, "shape", ())],
                         "dtype": str(getattr(arr, "dtype", "?")),
                         "nbytes": int(getattr(arr, "nbytes", 0))}
    return out


def _walk_files(root):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            yield os.path.join(dirpath, name)


def build_manifest(ckpt_dir, leaves=None, step=None, digest="sha256"):
    """Manifest dict over every file currently in `ckpt_dir` (except the
    manifest itself): relative path -> {size, sha256}. `leaves` is the
    per-leaf shape/dtype/nbytes metadata captured from the in-memory
    tree at save time."""
    files = {}
    for path in _walk_files(ckpt_dir):
        rel = os.path.relpath(path, ckpt_dir)
        if rel == MANIFEST_NAME:
            continue
        entry = {"size": os.path.getsize(path)}
        if digest == "sha256":
            entry["sha256"] = _sha256(path)
        files[rel.replace(os.sep, "/")] = entry
    return {"schema": MANIFEST_SCHEMA, "kind": "ckpt_manifest",
            "step": step, "time_unix": time.time(), "digest": digest,
            "leaves": leaves or {}, "files": files}


def load_manifest(ckpt_dir):
    with open(os.path.join(ckpt_dir, MANIFEST_NAME)) as f:
        return json.load(f)


def _leaf_for(rel, leaf_names):
    """Map a manifest file path to the leaf whose shard it holds. The
    orbax use_ocdbt=False layout keys each leaf's directory by its
    dotted name (`arrays/model.fc.weight/0.0`); longest-prefix match
    handles leaf names that are themselves dotted."""
    if not rel.startswith(ARRAYS_SUBDIR + "/"):
        return None
    sub = rel[len(ARRAYS_SUBDIR) + 1:]
    best = None
    for name in leaf_names:
        if (sub == name or sub.startswith(name + "/")) and \
                (best is None or len(name) > len(best)):
            best = name
    return best


def verify_checkpoint(ckpt_dir, deep=True):
    """Integrity-check one committed checkpoint against its manifest.

    Returns a list of problem strings ([] == valid); each names the
    offending file, and the leaf it belongs to when the orbax layout
    makes that mapping possible. `deep=False` skips content digests
    (size/presence only — the cheap scan a boot-time walk-back uses
    before committing to a full verify)."""
    problems = []
    if not os.path.isdir(ckpt_dir):
        return [f"{ckpt_dir}: not a directory"]
    mpath = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return [f"{MANIFEST_NAME} missing — checkpoint was never "
                "committed (or predates the manifest protocol)"]
    try:
        manifest = load_manifest(ckpt_dir)
    except (OSError, ValueError) as e:
        return [f"{MANIFEST_NAME} unreadable: {e}"]
    files = manifest.get("files")
    if not isinstance(files, dict) or not files:
        return [f"{MANIFEST_NAME} carries no file table"]
    leaf_names = list((manifest.get("leaves") or {}).keys())
    use_digest = deep and manifest.get("digest") == "sha256"
    for rel in sorted(files):
        meta = files[rel]
        path = os.path.join(ckpt_dir, *rel.split("/"))
        leaf = _leaf_for(rel, leaf_names)
        tag = f" (leaf {leaf})" if leaf else ""
        if not os.path.exists(path):
            problems.append(f"{rel}: missing{tag}")
            continue
        size = os.path.getsize(path)
        if size != meta.get("size"):
            problems.append(
                f"{rel}: truncated or resized — {size} bytes on disk vs "
                f"{meta.get('size')} in manifest{tag}")
            continue
        if use_digest and meta.get("sha256"):
            actual = _sha256(path)
            if actual != meta["sha256"]:
                problems.append(
                    f"{rel}: content digest mismatch — shard bytes were "
                    f"corrupted after write{tag}")
    return problems


def checkpoint_bytes(manifest):
    """Total payload bytes a manifest accounts for."""
    return sum(int(e.get("size", 0))
               for e in (manifest.get("files") or {}).values())


class _OcdbtNoiseFilter:
    """Drop orbax's per-save 'Skipping merge of OCDBT checkpoints'
    warning (expected under use_ocdbt=False; not actionable)."""

    def filter(self, record):
        try:
            return "Skipping merge of OCDBT" not in record.getMessage()
        except Exception:          # pragma: no cover - defensive
            return True


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------

class CheckpointManager:
    """Atomic, retrying, self-verifying step-checkpoint store.

        mgr = CheckpointManager(dir, model, optimizer, keep_last=3)
        ...
        mgr.save(step, run_state=rs)     # async kickoff; previous save
                                         # drains+commits first
        ...
        rs = mgr.restore()               # newest VALID checkpoint (auto
                                         # fallback past corrupt ones)

    keep_last    committed checkpoints retained (>=1)
    keep_every   additionally keep every N-th step forever (None: off)
    async_save   orbax AsyncCheckpointer (one instance, reused) vs sync
    retry        RetryPolicy for every I/O op (default: 4 attempts,
                 0.5s..30s full-jitter backoff)
    digest       'sha256' (default) or 'none' (size-only manifests)
    health       optional telemetry.HealthMonitor — every emitted
                 kind=ckpt record is also judged by its AnomalyDetector
                 (checkpoint_stall / checkpoint_failed rules)
    sink         optional JsonlSink or path for kind=ckpt records; when
                 absent, records ride the context-active recorder's sink
    """

    def __init__(self, directory, model=None, optimizer=None, keep_last=3,
                 keep_every=None, async_save=True, retry=None, rank=0,
                 digest="sha256", health=None, sink=None):
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.model = model
        self.optimizer = optimizer
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self.keep_last = int(keep_last)
        self.keep_every = int(keep_every) if keep_every else None
        self.async_save = bool(async_save)
        self.retry = retry or RetryPolicy()
        self.rank = int(rank)
        self.digest = digest
        self.health = health
        from ..telemetry.sink import JsonlSink
        self._owns_sink = isinstance(sink, str)
        self.sink = JsonlSink(sink) if self._owns_sink else sink
        self.records = []
        self._ckptr = None
        self._pending = None      # (step, tmp_dir, leaves, run_state, t0)
        self._gc_husks()

    # -- naming -------------------------------------------------------------
    def step_dir(self, step):
        return os.path.join(self.dir, f"{_STEP_PREFIX}{int(step)}")

    def _tmp_dir(self, step):
        return self.step_dir(step) + _TMP_SUFFIX

    def steps(self):
        """Committed (manifest-bearing) step numbers, ascending."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not name.startswith(_STEP_PREFIX) or name.endswith(_TMP_SUFFIX):
                continue
            try:
                step = int(name[len(_STEP_PREFIX):])
            except ValueError:
                continue
            if os.path.exists(os.path.join(self.dir, name, MANIFEST_NAME)):
                out.append(step)
        return sorted(out)

    def latest_step(self):
        """Newest committed step. The directory scan is AUTHORITATIVE:
        the atomic rename — not the `latest` marker — is the commit
        point, so a crash between the rename and the marker write must
        not make restore discard the just-committed step. The marker
        exists as a cheap hint for humans and external tooling; it is
        rewritten on every commit and never trusted over the scan."""
        steps = self.steps()
        return steps[-1] if steps else None

    # -- checkpointer (ONE instance — fixes the per-call leak) --------------
    def _checkpointer(self):
        if self._ckptr is None:
            import logging
            import orbax.checkpoint as ocp
            # use_ocdbt=False so each leaf owns a directory NAMED by its
            # flattened key — that naming is what lets verify_checkpoint
            # report a corrupt FILE as a corrupt LEAF. orbax logs a
            # harmless "skipping merge of OCDBT" warning per async save
            # in this mode; filter that one line, keep the rest.
            logging.getLogger("absl").addFilter(_OcdbtNoiseFilter())
            handler = ocp.PyTreeCheckpointHandler(use_ocdbt=False)
            self._ckptr = (ocp.AsyncCheckpointer(handler) if self.async_save
                           else ocp.Checkpointer(handler))
        return self._ckptr

    def _on_retry(self, attempt, exc, delay):
        monitor.incr("ckpt.retries")
        warnings.warn(
            f"[ckpt] transient I/O error (attempt {attempt}): "
            f"{type(exc).__name__}: {exc}; retrying in {delay:.2f}s",
            RuntimeWarning, stacklevel=4)

    def _io(self, fn, label):
        return with_retry(fn, policy=self.retry, on_retry=self._on_retry,
                          label=label)

    # -- save / commit ------------------------------------------------------
    def save(self, step, run_state=None, block=False):
        """Checkpoint the model (+optimizer) at `step`. Kicks off an
        async save and returns; the previous in-flight save is drained
        (committed) first, so at most one save is ever in flight.
        `block=True` (or async_save=False) commits before returning."""
        if self.model is None:
            raise CheckpointError("CheckpointManager has no model attached")
        self.drain()
        step = int(step)
        t0 = time.perf_counter()
        from ..distributed.checkpoint import _state_pytree
        tree = _state_pytree(self.model, self.optimizer)
        leaves = flatten_leaves(tree)
        if run_state is None:
            run_state = RunState(step=step).capture_rng()
        elif run_state.rng_state is None:
            run_state = run_state.snapshot()
        tmp = self._tmp_dir(step)
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        # NOTE: an already-committed step_N (a restart replayed this
        # step) is NOT touched here — the async save can fail or the
        # process can die before commit, and the committed checkpoint
        # must survive that. _commit moves it aside only at the moment
        # the replacement lands.

        def _kickoff():
            chaos.inject("save")
            self._checkpointer().save(
                os.path.join(tmp, ARRAYS_SUBDIR), tree, force=True)

        try:
            self._io(_kickoff, f"ckpt.save(step={step})")
        except Exception as e:
            self._failed(step, "save", e)
            raise (e if isinstance(e, CheckpointError) else
                   CheckpointError(f"checkpoint save at step {step} "
                                   f"failed: {e}")) from e
        monitor.incr("ckpt.saves")
        self._pending = (step, tmp, leaves, run_state, t0)
        self._emit("save", step)
        if block or not self.async_save:
            self.drain()
        return self

    def drain(self):
        """Wait out the in-flight async save and COMMIT it (manifest,
        fsync, atomic rename, latest marker, retention GC). A crash
        before drain loses only the uncommitted step — never corrupts a
        committed one."""
        if self._pending is None:
            return
        step, tmp, leaves, run_state, t0 = self._pending
        try:
            if self.async_save:
                self._io(self._checkpointer().wait_until_finished,
                         f"ckpt.wait(step={step})")
            self._commit(step, tmp, leaves, run_state, t0)
        except Exception as e:
            self._pending = None
            shutil.rmtree(tmp, ignore_errors=True)
            self._failed(step, "commit", e)
            raise (e if isinstance(e, CheckpointError) else
                   CheckpointError(f"checkpoint commit at step {step} "
                                   f"failed: {e}")) from e
        self._pending = None

    def _commit(self, step, tmp, leaves, run_state, t0):
        def _do_commit():
            chaos.inject("commit")
            _atomic_write_json(os.path.join(tmp, RUN_STATE_NAME),
                               run_state.to_dict())
            manifest = build_manifest(tmp, leaves=leaves, step=step,
                                      digest=self.digest)
            _atomic_write_json(os.path.join(tmp, MANIFEST_NAME), manifest)
            for path in _walk_files(tmp):
                _fsync_file(path)
            _fsync_dir(tmp)
            final = self.step_dir(step)
            # a restart that replayed this step supersedes the old
            # committed copy — but only NOW, with the replacement fully
            # written and verified-by-construction: move it aside (the
            # `.tmp` suffix puts a crash leftover under husk GC), land
            # the new one, then reap. The exposure window is two
            # renames, not the whole async save.
            aside = None
            if os.path.exists(final):
                aside = final + ".superseded" + _TMP_SUFFIX
                if os.path.exists(aside):
                    shutil.rmtree(aside, ignore_errors=True)
                os.replace(final, aside)
            os.replace(tmp, final)
            _fsync_dir(self.dir)
            if aside is not None:
                shutil.rmtree(aside, ignore_errors=True)
            return manifest

        manifest = self._io(_do_commit, f"ckpt.commit(step={step})")
        self._write_latest(step)
        save_ms = (time.perf_counter() - t0) * 1000.0
        nbytes = checkpoint_bytes(manifest)
        monitor.incr("ckpt.commits")
        monitor.set_gauge("ckpt.save_ms", save_ms)
        monitor.set_gauge("ckpt.bytes", float(nbytes))
        monitor.set_gauge("ckpt.last_step", float(step))
        self._emit("commit", step, save_ms=save_ms, bytes=nbytes)
        self._gc()

    def _write_latest(self, step):
        path = os.path.join(self.dir, LATEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(int(step)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.dir)

    def _failed(self, step, op, exc):
        monitor.incr("ckpt.failures")
        self._emit("failed", step, op=op,
                   error=f"{type(exc).__name__}: {exc}")

    # -- retention ----------------------------------------------------------
    def _gc(self):
        steps = self.steps()
        keep = set(steps[-self.keep_last:])
        if self.keep_every:
            keep.update(s for s in steps if s % self.keep_every == 0)
        removed = 0
        for s in steps:
            if s not in keep:
                shutil.rmtree(self.step_dir(s), ignore_errors=True)
                removed += 1
        removed += self._gc_husks()
        if removed:
            monitor.incr("ckpt.gc_removed", removed)
            self._emit("gc", steps[-1] if steps else 0, removed=removed)
        return removed

    def _gc_husks(self):
        """Reap uncommitted `.tmp` husks (crashed saves), sparing the
        one currently in flight."""
        live = self._pending[1] if self._pending is not None else None
        removed = 0
        try:
            names = os.listdir(self.dir)
        except OSError:
            return 0
        for name in names:
            if not (name.startswith(_STEP_PREFIX)
                    and name.endswith(_TMP_SUFFIX)):
                continue
            path = os.path.join(self.dir, name)
            if path == live or not os.path.isdir(path):
                continue
            shutil.rmtree(path, ignore_errors=True)
            removed += 1
        return removed

    # -- verify / restore ---------------------------------------------------
    def verify(self, step, deep=True):
        return verify_checkpoint(self.step_dir(step), deep=deep)

    def restore(self, step=None, model=None, optimizer=None, loader=None):
        """Restore model(+optimizer+RNG) in place; returns the RunState.

        step=None: newest VALID checkpoint — invalid ones (failed
        manifest verification) are skipped with a warning and counted
        as `ckpt.fallbacks`; returns None when no checkpoint exists at
        all; raises CheckpointCorruptError when checkpoints exist but
        none verifies. step=N: that exact checkpoint; corruption raises
        (explicit requests never silently fall back).

        `loader(arrays_path, model, optimizer)` overrides the array
        restore itself (default `distributed.checkpoint.load_checkpoint`)
        while keeping this method's verification, fallback, retry and
        telemetry semantics — the hook `resilience.reshard` routes its
        cross-layout restore through.
        """
        model = model if model is not None else self.model
        optimizer = optimizer if optimizer is not None else self.optimizer
        if model is None:
            raise CheckpointError("restore needs a model")
        if step is not None:
            problems = self.verify(step)
            if problems:
                raise CheckpointCorruptError(self.step_dir(step), problems)
            return self._restore_one(int(step), model, optimizer,
                                     loader=loader)
        steps = self.steps()
        if not steps:
            return None
        last_problems = None
        for s in sorted(steps, reverse=True):
            problems = self.verify(s)
            if problems:
                last_problems = (s, problems)
                monitor.incr("ckpt.fallbacks")
                self._emit("fallback", s, problems=problems[:8])
                warnings.warn(
                    f"[ckpt] checkpoint step {s} failed verification "
                    f"({problems[0]}" +
                    (f"; +{len(problems) - 1} more" if len(problems) > 1
                     else "") + "); falling back to an older checkpoint",
                    RuntimeWarning, stacklevel=2)
                continue
            return self._restore_one(s, model, optimizer, loader=loader)
        raise CheckpointCorruptError(
            self.step_dir(last_problems[0]), last_problems[1])

    def _restore_one(self, step, model, optimizer, loader=None):
        from ..distributed.checkpoint import load_checkpoint
        if loader is None:
            loader = load_checkpoint
        path = os.path.join(self.step_dir(step), ARRAYS_SUBDIR)
        t0 = time.perf_counter()

        def _load():
            chaos.inject("restore")
            return loader(path, model, optimizer)

        try:
            self._io(_load, f"ckpt.restore(step={step})")
        except Exception as e:
            self._failed(step, "restore", e)
            raise (e if isinstance(e, CheckpointError) else
                   CheckpointError(f"checkpoint restore at step {step} "
                                   f"failed: {e}")) from e
        rs_path = os.path.join(self.step_dir(step), RUN_STATE_NAME)
        run_state = RunState(step=step)
        if os.path.exists(rs_path):
            with open(rs_path) as f:
                run_state = RunState.from_dict(json.load(f))
        run_state.restore_rng()
        monitor.incr("ckpt.restores")
        monitor.set_gauge("ckpt.restore_ms",
                          (time.perf_counter() - t0) * 1000.0)
        self._emit("restore", step)
        return run_state

    # -- record plumbing ----------------------------------------------------
    def _emit(self, event, step, **fields):
        from ..telemetry.sink import emit_record, make_ckpt_record
        rec = make_ckpt_record(event=event, step=step, rank=self.rank,
                               **fields)
        self.records.append(rec)
        emit_record(rec, self.sink)
        if self.health is not None:
            # the same kind=ckpt record the JSONL carries is judged
            # in-flight, so live paging and offline replay agree
            self.health.observe_record(rec)
        return rec

    # -- lifecycle ----------------------------------------------------------
    def close(self):
        """Drain + release the checkpointer (its background threads)."""
        try:
            self.drain()
        finally:
            if self._ckptr is not None:
                try:
                    self._ckptr.close()
                except Exception:
                    pass
                self._ckptr = None
            if self.sink is not None and self._owns_sink:
                self.sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
