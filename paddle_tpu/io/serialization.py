"""paddle.save / paddle.load analog.

Parity: `python/paddle/framework/io.py:550,766` — pickle protocol with
tensors converted to numpy. Orbax-based sharded/async checkpointing for
distributed training lives in `paddle_tpu.distributed.checkpoint`.
"""
import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _SavedTensor(np.asarray(obj._value), obj.name)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def _from_saved(obj, return_numpy=False):
    if isinstance(obj, _SavedTensor):
        if return_numpy:
            return obj.array
        t = Tensor(obj.array)
        t.name = obj.name
        return t
    if isinstance(obj, dict):
        return {k: _from_saved(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saved(v, return_numpy) for v in obj)
    return obj


class _SavedTensor:
    __slots__ = ("array", "name")

    def __init__(self, array, name=None):
        self.array = array
        self.name = name


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saved(obj, return_numpy)
