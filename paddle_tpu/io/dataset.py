"""Slot-based dataset ingestion for the parameter-server path.

Parity target: `python/paddle/fluid/dataset.py` (DatasetBase:65,
InMemoryDataset:364, QueueDataset:1004) and the C++ MultiSlotDataFeed
behind them (`paddle/fluid/framework/data_feed.cc`). The reference feeds
a C++ trainer via protobuf descriptors; here the contract is TPU-first:
batches come out as dense numpy arrays (sparse slots padded to
[batch, max_len] int64 with a mask) ready to feed jnp / the
DistributedEmbedding pull path in one host->device transfer.

Text line format (the classic CTR layout):
    <label> <slot>:<feasign> <slot>:<feasign> ...
Sparse slots collect variable-length id lists per example; dense slots
parse the value as float. `set_pipe_command` pipes each file through a
shell command first (reference DatasetBase.set_pipe_command:80).
"""
import os
import subprocess
import threading
import queue as _queue

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset",
           "BoxPSDataset", "SlotDesc", "dataset_factory"]


class SlotDesc:
    """One input slot: sparse (id list) or dense (single float)."""

    def __init__(self, name, is_sparse=True, max_len=16, dtype=None):
        self.name = name
        self.is_sparse = is_sparse
        self.max_len = max_len
        self.dtype = dtype or (np.int64 if is_sparse else np.float32)

    def __repr__(self):
        kind = "sparse" if self.is_sparse else "dense"
        return f"SlotDesc({self.name}, {kind})"


class DatasetBase:
    """Reference `dataset.py:65` DatasetBase API surface."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist = []
        self.slots = []
        self.pipe_command = None
        self.drop_last = False

    # ---- reference setters ----
    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = max(1, int(thread_num))

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        """Accepts SlotDesc objects or names (names default to sparse)."""
        self.slots = [v if isinstance(v, SlotDesc) else SlotDesc(str(v))
                      for v in var_list]

    def set_pipe_command(self, pipe_command):
        self.pipe_command = pipe_command

    def set_hdfs_config(self, fs_name, fs_ugi):
        raise NotImplementedError(
            "paddle_tpu datasets read local/NFS/GCS-mounted files; "
            "HDFS ingestion is out of scope (stage files locally)")

    # ---- parsing ----
    def _read_lines(self, path):
        if self.pipe_command:
            proc = subprocess.run(
                f"{self.pipe_command} < {path!r}", shell=True,
                capture_output=True, text=True, check=True)
            return proc.stdout.splitlines()
        with open(path) as f:
            return f.read().splitlines()

    def _parse_line(self, line):
        toks = line.split()
        if not toks:
            return None
        rec = {"label": np.float32(toks[0])}
        sparse = {s.name: [] for s in self.slots if s.is_sparse}
        for t in toks[1:]:
            slot, _, val = t.partition(":")
            if not val:
                continue
            if slot in sparse:
                sparse[slot].append(int(val))
            else:
                rec[slot] = np.float32(val)
        rec.update(sparse)
        return rec

    def _batchify(self, records):
        """records -> dict of arrays: label [B], sparse [B, max_len] int64
        (padded 0) + <slot>_mask [B, max_len] f32, dense [B] f32."""
        B = len(records)
        out = {"label": np.asarray([r["label"] for r in records],
                                   np.float32)}
        for s in self.slots:
            if s.is_sparse:
                ids = np.zeros((B, s.max_len), np.int64)
                mask = np.zeros((B, s.max_len), np.float32)
                for i, r in enumerate(records):
                    v = r.get(s.name, [])[:s.max_len]
                    ids[i, :len(v)] = v
                    mask[i, :len(v)] = 1.0
                out[s.name] = ids
                out[s.name + "_mask"] = mask
            else:
                out[s.name] = np.asarray(
                    [r.get(s.name, 0.0) for r in records], np.float32)
        return out


class InMemoryDataset(DatasetBase):
    """Reference `dataset.py:364`: load everything, shuffle in memory,
    iterate epochs. global_shuffle redistributes records across trainers
    by hash (here: deterministic hash-mod over the fleet world size)."""

    def __init__(self):
        super().__init__()
        self._records = []
        self._rng = np.random.RandomState(0)

    def load_into_memory(self, is_shuffle=False):
        self._records = []
        for path in self.filelist:
            for line in self._read_lines(path):
                rec = self._parse_line(line)
                if rec is not None:
                    self._records.append(rec)
        if is_shuffle:
            self.local_shuffle()

    def set_shuffle_seed(self, seed):
        self._rng = np.random.RandomState(int(seed))

    def local_shuffle(self):
        self._rng.shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num=None):
        """Keep only this trainer's shard (hash-mod), then shuffle —
        the stateless equivalent of the reference's cross-trainer
        record exchange (`dataset.py:816`)."""
        if fleet is not None:
            rank = fleet.worker_index()
            world = fleet.worker_num()
        else:
            rank, world = 0, 1
        if world > 1:
            self._records = [r for i, r in enumerate(self._records)
                             if i % world == rank]
        self.local_shuffle()

    def release_memory(self):
        self._records = []

    def get_memory_data_size(self, fleet=None):
        return len(self._records)

    def __len__(self):
        return len(self._records)

    def __iter__(self):
        for i in range(0, len(self._records), self.batch_size):
            chunk = self._records[i:i + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            yield self._batchify(chunk)


class QueueDataset(DatasetBase):
    """Reference `dataset.py:1004`: streaming — reader threads parse
    files into a bounded queue, the consumer drains batches; nothing is
    retained (single-pass, constant memory)."""

    QUEUE_DEPTH = 64

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset is single-pass streaming; use InMemoryDataset "
            "for shuffling (reference raises the same way, "
            "dataset.py:1041)")

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            "QueueDataset cannot global-shuffle (reference "
            "dataset.py:1063); shard the filelist across trainers")

    def __iter__(self):
        q = _queue.Queue(maxsize=self.QUEUE_DEPTH)
        SENTINEL = object()
        files = list(self.filelist)
        lock = threading.Lock()

        def reader():
            while True:
                with lock:
                    if not files:
                        break
                    path = files.pop(0)
                for line in self._read_lines(path):
                    rec = self._parse_line(line)
                    if rec is not None:
                        q.put(rec)
            q.put(SENTINEL)

        n = min(self.thread_num, max(1, len(self.filelist)))
        threads = [threading.Thread(target=reader, daemon=True)
                   for _ in range(n)]
        for t in threads:
            t.start()
        done = 0
        buf = []
        while done < n:
            item = q.get()
            if item is SENTINEL:
                done += 1
                continue
            buf.append(item)
            if len(buf) == self.batch_size:
                yield self._batchify(buf)
                buf = []
        if buf and not self.drop_last:
            yield self._batchify(buf)
        for t in threads:
            t.join()


def dataset_factory(name):
    """Reference DatasetFactory.create_dataset analog."""
    table = {"InMemoryDataset": InMemoryDataset,
             "QueueDataset": QueueDataset,
             "BoxPSDataset": BoxPSDataset}  # resolved at call time
    if name not in table:
        raise ValueError(f"unknown dataset type {name!r}; "
                         f"one of {sorted(table)}")
    return table[name]()


class BoxPSDataset(InMemoryDataset):
    """BoxPS-flavored in-memory dataset (reference `fluid/dataset.py:1128`).
    The BoxPS GPU-cache machinery dissolves on TPU (embeddings ride the
    pskv host tables); the data-side API — begin/end pass bracketing over
    an in-memory shuffled dataset — is preserved."""

    def begin_pass(self):
        if not getattr(self, "_records", None):
            self.load_into_memory()

    def end_pass(self, need_save_delta=False):
        pass

    def wait_preload_done(self):
        pass

    def preload_into_memory(self, file_num=None):
        self.load_into_memory()
