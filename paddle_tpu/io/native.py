"""ctypes binding for the native IO runtime (csrc/ptio.cc).

The reference keeps its data path in C++ (DataFeed channels
`framework/data_feed.cc`, in-memory Dataset `data_set.cc`, double-buffered
`reader/buffered_reader.h`); this is the TPU-native counterpart: record
datasets are written once, mmap'd, and batches are gathered by C++ worker
threads into pooled aligned staging buffers that Python hands directly to
the device transfer. Built on demand with g++ (no pybind dependency).
"""
import ctypes
import os
import subprocess
import threading

import numpy as np

_DTYPES = {  # code <-> numpy dtype (must match elem_size_of in ptio.cc)
    0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64,
    4: np.uint8, 5: np.float16, 6: np.int16, 7: np.int8,
}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}

_lib = None
_lib_lock = threading.Lock()


def _build_lib():
    from ..utils.native_build import native_lib_path
    return native_lib_path("ptio")


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_build_lib())
        lib.ptio_writer_open.restype = ctypes.c_void_p
        lib.ptio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int32,
                                         ctypes.c_int32,
                                         ctypes.POINTER(ctypes.c_int64)]
        lib.ptio_writer_append.restype = ctypes.c_int64
        lib.ptio_writer_append.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                           ctypes.c_int64]
        lib.ptio_writer_close.argtypes = [ctypes.c_void_p]
        lib.ptio_open.restype = ctypes.c_void_p
        lib.ptio_open.argtypes = [ctypes.c_char_p]
        lib.ptio_count.restype = ctypes.c_int64
        lib.ptio_count.argtypes = [ctypes.c_void_p]
        lib.ptio_dtype.restype = ctypes.c_int32
        lib.ptio_dtype.argtypes = [ctypes.c_void_p]
        lib.ptio_ndim.restype = ctypes.c_int32
        lib.ptio_ndim.argtypes = [ctypes.c_void_p]
        lib.ptio_dims.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int64)]
        lib.ptio_close.argtypes = [ctypes.c_void_p]
        lib.ptio_loader_create.restype = ctypes.c_void_p
        lib.ptio_loader_create.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32]
        lib.ptio_loader_next.restype = ctypes.c_int64
        lib.ptio_loader_next.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_void_p),
                                         ctypes.POINTER(ctypes.c_void_p)]
        lib.ptio_batch_release.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.ptio_loader_reset.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ptio_loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def native_available():
    try:
        _load()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


def write_dataset(path, array):
    """Write a [N, ...] numpy array as a PTIO record file."""
    lib = _load()
    arr = np.ascontiguousarray(array)
    code = _CODES.get(arr.dtype)
    if code is None:
        raise TypeError(f"unsupported dtype {arr.dtype}")
    dims = (ctypes.c_int64 * 8)(*arr.shape[1:], *([0] * (8 - arr.ndim + 1)))
    w = lib.ptio_writer_open(path.encode(), code, arr.ndim - 1, dims)
    if not w:
        raise OSError(f"cannot open {path} for writing")
    n = lib.ptio_writer_append(
        w, arr.ctypes.data_as(ctypes.c_void_p), arr.shape[0])
    lib.ptio_writer_close(w)
    if n != arr.shape[0]:
        raise OSError(f"short write to {path}: {n}/{arr.shape[0]}")
    return path


class RecordDataset:
    """mmap'd PTIO file."""

    def __init__(self, path):
        self._lib = _load()
        self._h = self._lib.ptio_open(path.encode())
        if not self._h:
            raise OSError(f"cannot open PTIO dataset {path}")
        self.path = path
        nd = self._lib.ptio_ndim(self._h)
        dims = (ctypes.c_int64 * 8)()
        self._lib.ptio_dims(self._h, dims)
        self.sample_shape = tuple(dims[i] for i in range(nd))
        self.dtype = np.dtype(_DTYPES[self._lib.ptio_dtype(self._h)])

    def __len__(self):
        return int(self._lib.ptio_count(self._h))

    def close(self):
        if self._h:
            self._lib.ptio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeDataLoader:
    """Threaded prefetching loader over one or more zipped PTIO files.

    Yields tuples of numpy arrays (one per file). The arrays VIEW pooled
    staging buffers and are valid until the next iteration step (pass
    copy=True to detach). Epochs reshuffle deterministically from
    seed + epoch.
    """

    def __init__(self, paths, batch_size, shuffle=False, seed=0,
                 num_threads=4, capacity=None, drop_last=True, copy=False):
        if capacity is None:
            from ..flags import get_flag
            capacity = max(2, get_flag("io_prefetch_capacity"))
        self._lib = _load()
        paths = [paths] if isinstance(paths, str) else list(paths)
        self.datasets = [RecordDataset(p) for p in paths]
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.copy = copy
        self._epoch = 0
        self._ticket = None
        handles = (ctypes.c_void_p * len(self.datasets))(
            *[d._h for d in self.datasets])
        self._h = self._lib.ptio_loader_create(
            handles, len(self.datasets), self.batch_size,
            1 if shuffle else 0, seed, num_threads, capacity,
            1 if drop_last else 0)
        if not self._h:
            raise OSError("loader creation failed")
        n = min(len(d) for d in self.datasets)
        self._num_batches = n // self.batch_size if drop_last else \
            -(-n // self.batch_size)

    def __len__(self):
        return self._num_batches

    def _release(self):
        if self._ticket is not None:
            self._lib.ptio_batch_release(self._h, self._ticket)
            self._ticket = None

    def __iter__(self):
        if self._epoch > 0:
            self._release()
            self._lib.ptio_loader_reset(self._h, self.seed + self._epoch)
        self._epoch += 1
        out_ptrs = (ctypes.c_void_p * len(self.datasets))()
        ticket = ctypes.c_void_p()
        while True:
            self._release()
            n = self._lib.ptio_loader_next(self._h, out_ptrs,
                                           ctypes.byref(ticket))
            if n <= 0:
                if n < 0:
                    raise RuntimeError("native loader stopped")
                return
            self._ticket = ticket.value
            arrs = []
            for d, ds in enumerate(self.datasets):
                shape = (n,) + ds.sample_shape
                nbytes = int(np.prod(shape)) * ds.dtype.itemsize
                buf = (ctypes.c_char * nbytes).from_address(out_ptrs[d])
                a = np.frombuffer(buf, dtype=ds.dtype).reshape(shape)
                arrs.append(a.copy() if self.copy else a)
            yield tuple(arrs)

    def close(self):
        self._release()
        if getattr(self, "_h", None):
            self._lib.ptio_loader_destroy(self._h)
            self._h = None
        for d in self.datasets:
            d.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
