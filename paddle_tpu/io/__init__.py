"""paddle_tpu.io — mirrors `python/paddle/io/`."""
from .dataloader import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ConcatDataset,
    ChainDataset, Subset, random_split, Sampler, SequenceSampler,
    RandomSampler, WeightedRandomSampler, BatchSampler,
    DistributedBatchSampler, DataLoader, default_collate_fn, get_worker_info,
)
from .prefetch import (  # noqa: F401
    DeviceLoader, WorkerInfo, default_collate_numpy, device_put_batch,
    prefetch_to_device,
)
from .serialization import save, load  # noqa: F401
from .dataset import (  # noqa: F401
    DatasetBase, InMemoryDataset, QueueDataset, SlotDesc, dataset_factory,
)
from .crypto import encrypt_save, decrypt_load, CryptoError  # noqa: F401

# native (C++) record-file data path — threaded prefetch into staging
# buffers (csrc/ptio.cc); importing is lazy so g++ is only needed on use
from . import native  # noqa: F401
