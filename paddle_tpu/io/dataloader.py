"""Dataset / Sampler / DataLoader.

Parity: `python/paddle/fluid/reader.py:146` DataLoader +
`python/paddle/fluid/dataloader/` (dataset.py, batch_sampler.py, worker
processes with shared-mem mmap tensors). TPU-native differences: batches are
collated into numpy on host workers and transferred once per step (minimizing
host->HBM traffic); multi-process workers use the standard multiprocessing
pool rather than the reference's custom mmap allocator
(`memory/allocation/mmap_allocator.cc`) because JAX owns device transfer.
"""
import itertools
import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from ..core.random import default_generator


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(t._value)[idx] if isinstance(t, Tensor)
                     else np.asarray(t)[idx] for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return t.shape[0] if isinstance(t, Tensor) else len(t)


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        ds = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds == 0 else int(self.cum[ds - 1])
        return self.datasets[ds][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    if total != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(self.weights), self.num_samples,
                                     replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-rank sharding of the sample space (reference
    `python/paddle/io/DistributedBatchSampler`); on TPU used for per-host
    data feeding of a dp-sharded global batch."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from .. import distributed as dist
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            dist.get_world_size()
        self.local_rank = rank if rank is not None else dist.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        # pad to be divisible
        pad = self.total_size - n
        if pad > 0:
            indices = np.concatenate([indices, indices[:pad]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(b._value) for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    return batch


class DataLoader:
    """Iterates a Dataset into device Tensors.

    Map-style datasets with num_workers>0 run an asynchronous prefetch
    pipeline (io.prefetch): worker THREADS by default (the numpy decode
    path releases the GIL), or real worker PROCESSES over a fork-safe
    start method with shared-memory batch transport
    (``worker_mode="process"``, picklable dataset required). Batches are
    delivered in sampler order regardless of worker completion order,
    so the stream is deterministic in num_workers for a fixed seed.
    Iterable datasets use a background-thread prefetch pipeline (the
    reference's BufferedReader double-buffering,
    `operators/reader/buffered_reader.h:36`).

    DEPRECATED (PR 6): the old fork-context worker pool is gone —
    ``os.fork()`` under multithreaded JAX is a deadlock hazard
    (BENCH_r04/r05 RuntimeWarning) — and ``worker_mode="fork"`` raises.
    The constructor surface is otherwise unchanged;
    ``use_shared_memory`` now gates the preallocated shared-memory slot
    transport of process workers (ignored for threads).

    BEHAVIOR CHANGE vs the fork pool: the default ``worker_mode="auto"``
    runs worker THREADS that share ONE dataset object (the fork workers
    each had a copy-on-write copy). A dataset with per-instance mutable
    state (its own RandomState, parser buffers, file handles) must pass
    ``worker_mode="process"`` to get per-worker copies back — thread
    workers calling ``__getitem__`` concurrently on such a dataset race.

    A ``persistent_workers`` loader supports ONE active iterator at a
    time (they share the worker pool): starting a new epoch drains and
    invalidates the previous iterator, whose ``next()`` then raises.

    For training loops, wrap the loader in
    ``io.prefetch_to_device(loader, sharding=...)`` to overlap the H2D
    transfer with compute and land each dp shard directly on its device.
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, use_shared_memory=True,
                 prefetch_factor=2, timeout=0, worker_init_fn=None,
                 persistent_workers=False, worker_mode="auto"):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch = max(2, prefetch_factor)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self.persistent_workers = persistent_workers
        self.worker_mode = worker_mode
        self.device_sharding = None   # set by prefetch.DeviceLoader/callers
        self._pool = None
        self._active_iter = None      # weakref: persistent-workers guard
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _batches(self):
        from .. import monitor
        if self._iterable_mode:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    monitor.incr("io.batches")
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                monitor.incr("io.batches")
                yield self.collate_fn(batch)
            return
        for indices in self.batch_sampler:
            monitor.incr("io.batches")
            yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.num_workers == 0:
            return self._batches()
        if self._iterable_mode:
            return self._iterable_prefetch()
        from .prefetch import MultiWorkerIterator, make_pool
        if self.persistent_workers:
            # one ACTIVE iterator at a time: two iterators sharing the
            # persistent pool would steal each other's results off the
            # single result queue and deadlock — drain and invalidate
            # the previous one before feeding new jobs
            prev = self._active_iter() if self._active_iter else None
            if prev is not None:
                prev._invalidate()
        if self._pool is None or not self.persistent_workers:
            self._pool = make_pool(self)
        it = MultiWorkerIterator(self, self._pool)
        if self.persistent_workers:
            import weakref
            self._active_iter = weakref.ref(it)
        return it

    def _iterable_prefetch(self):
        """Iterable datasets: one background producer thread feeding a
        bounded queue (backpressure = prefetch depth), waits recorded
        for the flight recorder."""
        import time as _time
        from .prefetch import _WaitTracker
        q = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        err = []

        def producer():
            try:
                for b in self._batches():
                    q.put(b)
            except BaseException as e:
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True,
                             name="paddle-io-iterable-prefetch")
        t.start()
        wait = _WaitTracker()
        while True:
            t0 = _time.perf_counter()
            item = q.get()
            if item is sentinel:
                if err:
                    raise err[0]
                break
            wait.fetched(_time.perf_counter() - t0, q.qsize())
            yield item

    # -- hooks used by io.prefetch ---------------------------------------
    def _leaf_transfer(self, sharding=None):
        """Process-pool finalize hook: move one batch's ndarray leaves
        (views into a shared-memory slot) onto the device and block
        until the copy lands — the slot is recycled right after."""
        from .prefetch import _leaf_put
        import jax
        put = _leaf_put(sharding)
        # the CPU client zero-copy-aliases aligned host buffers instead
        # of copying them; a device array aliasing a recycled slot is a
        # use-after-unmap, so on host-resident backends the leaf must be
        # copied out first. Real accelerators DMA the bytes to HBM —
        # there the view-to-device_put path is the zero-copy win.
        aliases_host = jax.default_backend() == "cpu"

        def xfer(leaves):
            if aliases_host:
                leaves = [np.array(a) for a in leaves]
            out = [put(a) for a in leaves]
            if out:
                jax.block_until_ready(out)
            return out
        return xfer

    def _wrap_leaves(self, tree):
        """Wrap array leaves of a worker-collated batch into Tensors so
        process-worker output matches default_collate_fn's exactly."""
        import jax

        def wrap(node):
            if isinstance(node, (np.ndarray, jax.Array)):
                return Tensor(node)
            if isinstance(node, tuple):
                return tuple(wrap(x) for x in node)
            if isinstance(node, list):
                return [wrap(x) for x in node]
            if isinstance(node, dict):
                return {k: wrap(v) for k, v in node.items()}
            return node
        return wrap(tree)

    def shutdown(self):
        """Tear down persistent workers (idempotent)."""
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown()

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


def get_worker_info():
    """Inside a worker (thread or process): that worker's WorkerInfo
    (id, num_workers, seed, dataset); None in the main process."""
    from .prefetch import get_worker_info as _gwi
    return _gwi()
