"""Dataset / Sampler / DataLoader.

Parity: `python/paddle/fluid/reader.py:146` DataLoader +
`python/paddle/fluid/dataloader/` (dataset.py, batch_sampler.py, worker
processes with shared-mem mmap tensors). TPU-native differences: batches are
collated into numpy on host workers and transferred once per step (minimizing
host->HBM traffic); multi-process workers use the standard multiprocessing
pool rather than the reference's custom mmap allocator
(`memory/allocation/mmap_allocator.cc`) because JAX owns device transfer.
"""
import itertools
import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from ..core.random import default_generator


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(t._value)[idx] if isinstance(t, Tensor)
                     else np.asarray(t)[idx] for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return t.shape[0] if isinstance(t, Tensor) else len(t)


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        ds = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds == 0 else int(self.cum[ds - 1])
        return self.datasets[ds][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    if total != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(self.weights), self.num_samples,
                                     replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-rank sharding of the sample space (reference
    `python/paddle/io/DistributedBatchSampler`); on TPU used for per-host
    data feeding of a dp-sharded global batch."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from .. import distributed as dist
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            dist.get_world_size()
        self.local_rank = rank if rank is not None else dist.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        # pad to be divisible
        pad = self.total_size - n
        if pad > 0:
            indices = np.concatenate([indices, indices[:pad]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(b._value) for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    return batch


def _mp_worker_loop(dataset, index_q, result_q, worker_init_fn, wid):
    """Worker PROCESS: fetch raw samples for each index batch; the parent
    collates (keeps the pickle payload to raw numpy/py objects). Reference
    analog: `fluid/dataloader/worker.py` _worker_loop."""
    if worker_init_fn is not None:
        worker_init_fn(wid)
    while True:
        job = index_q.get()
        if job is None:
            break
        seq, indices = job
        try:
            samples = [dataset[i] for i in indices]
            result_q.put((seq, samples, None))
        except Exception as e:  # surface the worker error in the parent
            result_q.put((seq, None, f"{type(e).__name__}: {e}"))


class DataLoader:
    """Iterates a Dataset into device Tensors.

    Map-style datasets with num_workers>0 fetch samples in real WORKER
    PROCESSES (reference `fluid/dataloader/worker.py` semantics — python
    transforms escape the GIL); batches are delivered in sampler order
    regardless of worker completion order. Iterable datasets use a
    background-thread prefetch pipeline (the reference's BufferedReader
    double-buffering, `operators/reader/buffered_reader.h:36`).
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, use_shared_memory=True,
                 prefetch_factor=2, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch = max(2, prefetch_factor)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _batches(self):
        from .. import monitor
        if self._iterable_mode:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    monitor.incr("io.batches")
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                monitor.incr("io.batches")
                yield self.collate_fn(batch)
            return
        for indices in self.batch_sampler:
            monitor.incr("io.batches")
            yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._batches()
            return
        if not self._iterable_mode:
            import multiprocessing as mp
            if "fork" in mp.get_all_start_methods():
                # fork-context workers inherit the dataset — no pickling
                # of the dataset object itself, so arbitrary python
                # datasets work
                yield from self._process_iter()
                return
            # no fork (Windows): thread prefetch below still works
        # background-thread prefetch pipeline
        q = queue.Queue(maxsize=self.prefetch)
        sentinel = object()

        def producer():
            try:
                for b in self._batches():
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item

    def _process_iter(self):
        """Real worker processes; results reordered to sampler order.
        Index feeding has backpressure (<= num_workers * prefetch jobs in
        flight) and result waits poll worker liveness so a killed worker
        raises instead of hanging."""
        import multiprocessing as mp
        import queue as _q
        from .. import monitor
        ctx = mp.get_context("fork")
        index_q = ctx.Queue()
        result_q = ctx.Queue()
        workers = [ctx.Process(
            target=_mp_worker_loop,
            args=(self.dataset, index_q, result_q, self.worker_init_fn,
                  wid),
            daemon=True) for wid in range(self.num_workers)]
        for w in workers:
            w.start()
        deadline = self.timeout or None
        try:
            jobs = enumerate(self.batch_sampler)
            n_sent = 0
            n_jobs = len(self.batch_sampler)
            exhausted = False

            def feed(limit):
                nonlocal n_sent, exhausted
                while not exhausted and n_sent - next_seq < limit:
                    try:
                        seq, indices = next(jobs)
                    except StopIteration:
                        exhausted = True
                        for _ in workers:
                            index_q.put(None)
                        return
                    index_q.put((seq, list(indices)))
                    n_sent += 1

            pending = {}
            next_seq = 0
            limit = max(2, self.num_workers * self.prefetch)
            feed(limit)
            while next_seq < n_jobs:
                if next_seq in pending:
                    samples = pending.pop(next_seq)
                    next_seq += 1
                    feed(limit)
                    monitor.incr("io.batches")
                    yield self.collate_fn(samples)
                    continue
                try:
                    seq, samples, err = result_q.get(
                        timeout=deadline or 5.0)
                except _q.Empty:
                    dead = [w for w in workers if not w.is_alive()]
                    if dead or deadline:
                        raise RuntimeError(
                            f"DataLoader worker(s) "
                            f"{[w.pid for w in dead]} died or timed out "
                            f"waiting {deadline or 5.0}s for batch "
                            f"{next_seq}") from None
                    continue
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed: {err}")
                pending[seq] = samples
        finally:
            for w in workers:
                w.terminate()
            for w in workers:
                w.join()


def get_worker_info():
    return None
