"""Asynchronous prefetch-to-device input pipeline (the DataLoader engine).

Rebuilt from the fork-based worker pool (PR 6): `os.fork()` under a
multithreaded JAX runtime is a real deadlock hazard (the BENCH_r04/r05
RuntimeWarning), so no code path here ever forks the parent. Three
worker transports, chosen per loader:

- **thread** (default): N worker threads fetch + collate batches. The
  hot decode path is numpy (slice/copy/stack release the GIL), so
  threads scale for array-heavy transforms and cost nothing to spawn.
- **process** (``worker_mode="process"``/"spawn"/"forkserver"): real
  worker PROCESSES started via a fork-safe context (forkserver's server
  is exec'd, spawn is exec'd — neither calls `os.fork()` in the
  multithreaded parent). Batches come back through PREALLOCATED shared-
  memory slots: the worker collates samples straight into the slot
  buffer (zero-copy assembly — no per-batch pickle of array payloads),
  the parent maps numpy views onto the slot and moves them to the
  device, then recycles the slot. Slot count bounds the jobs in flight,
  so backpressure falls out of slot availability. Requires a picklable
  dataset; ``worker_mode="auto"`` falls back to threads when the
  dataset cannot be shipped.
- **num_workers=0**: synchronous in-caller iteration (unchanged).

On top of either transport, `DeviceLoader` / `prefetch_to_device()` is
the double-buffered device iterator: a background stage keeps `size`
batches device-resident (``jax.device_put`` with an explicit Sharding,
so a dp-sharded batch lands shard-by-shard on its devices with no
host-side gather/re-split) while step N's compute runs, and every
``next()`` records how long the consumer waited on input:

- ``io.input_wait_ms`` / ``io.queue_depth`` / ``io.input_bound_frac``
  monitor gauges (live on the PR-3 ``/metrics`` endpoint);
- the same three fields land first-class in the step-record JSONL via
  the telemetry recorder (sink.STEP_OPTIONAL_KEYS), so "host-bound vs
  chip-bound" is a number in the flight recorder, not a vibe.

Worker processes never touch an accelerator: they produce numpy only,
and never initialize a JAX backend (`JAX_PLATFORMS` is pinned to cpu in
the child before the dataset is even unpickled).
"""
import collections
import itertools
import os
import pickle
import queue as _queue
import threading
import time
import weakref

import numpy as np

__all__ = [
    "DeviceLoader", "prefetch_to_device", "WorkerInfo", "get_worker_info",
    "default_collate_numpy", "consume_step_input_stats",
]

# --------------------------------------------------------------------------
# worker identity (paddle.io.get_worker_info analog)
# --------------------------------------------------------------------------

class WorkerInfo:
    """Identity of the worker executing the current ``__getitem__`` /
    dataset iteration: ``id`` in [0, num_workers), ``num_workers``,
    ``seed`` (per-worker), ``dataset`` (this worker's copy)."""

    def __init__(self, id, num_workers, seed=None, dataset=None):  # noqa: A002
        self.id = int(id)
        self.num_workers = int(num_workers)
        self.seed = seed
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, "
                f"num_workers={self.num_workers}, seed={self.seed})")


_PROC_WORKER_INFO = None            # set in worker processes
_THREAD_WORKER_INFO = threading.local()


def get_worker_info():
    """Inside a worker (thread or process): its WorkerInfo; None in the
    main process/thread."""
    info = getattr(_THREAD_WORKER_INFO, "info", None)
    if info is not None:
        return info
    return _PROC_WORKER_INFO


# --------------------------------------------------------------------------
# input-wait telemetry shared with the flight recorder
# --------------------------------------------------------------------------

_INPUT_LOCK = threading.Lock()
_INPUT_STATS = None                 # guarded by: _INPUT_LOCK — most recent batch-fetch stats
_INTERIOR = threading.local()       # set in pipeline-internal threads


def _note_input_stats(wait_ms, depth, frac):
    """Record the fetch stats of the batch about to be consumed. The
    telemetry recorder pops these at step close (consume_step_input_stats)
    so they land first-class in that step's JSONL record. ONE process-
    global slot — latest fetch wins — so a consumer interleaving loaders
    (e.g. an eval pass inside fit) must drop the stale value before its
    next recorded step (hapi drains after every eval pass)."""
    global _INPUT_STATS
    from .. import monitor
    monitor.set_gauge("io.input_wait_ms", wait_ms)
    monitor.set_gauge("io.queue_depth", depth)
    monitor.set_gauge("io.input_bound_frac", frac)
    with _INPUT_LOCK:
        _INPUT_STATS = {"input_wait_ms": round(float(wait_ms), 4),
                        "input_queue_depth": int(depth),
                        "input_bound_frac": round(float(frac), 4)}


def consume_step_input_stats():
    """Pop the most recent batch-fetch stats (one-shot; None when no
    loader delivered a batch since the last pop). Called by
    TelemetryRecorder.end_step so the fields describe THIS step's input
    wait, not a stale one."""
    global _INPUT_STATS
    with _INPUT_LOCK:
        stats, _INPUT_STATS = _INPUT_STATS, None
    return stats


class _WaitTracker:
    """Per-iterator input-wait accounting: instantaneous wait per fetch
    plus an EMA input-bound fraction (wait / (wait + compute))."""

    def __init__(self, alpha=0.25):
        self.alpha = alpha
        self.frac = 0.0
        self._last_return = None

    def fetched(self, wait_s, depth):
        now = time.perf_counter()
        busy_s = 0.0
        if self._last_return is not None:
            busy_s = max(0.0, now - self._last_return - wait_s)
        inst = wait_s / max(1e-9, wait_s + busy_s)
        self.frac += self.alpha * (inst - self.frac)
        self._last_return = now
        # only the CONSUMER-facing end of the pipeline reports: a host
        # iterator being drained by a DeviceLoader stage thread would
        # otherwise race its (large, background) waits into the same
        # one-shot slot and invert the host-bound signal
        if getattr(_INTERIOR, "on", False):
            return
        _note_input_stats(wait_s * 1000.0, depth, self.frac)


# --------------------------------------------------------------------------
# numpy-side collate (runs in workers; no jax, no Tensor construction)
# --------------------------------------------------------------------------

def default_collate_numpy(batch):
    """Structure-preserving collate to NUMPY (the worker-side half of
    io.default_collate_fn): nested tuples/lists/dicts of arrays/scalars
    become stacked ndarrays; the parent wraps array leaves into device
    Tensors. Tensor leaves are read out via np.asarray so workers never
    build device arrays."""
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_numpy([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_numpy([b[k] for b in batch])
                for k in sample}
    if hasattr(sample, "_value"):       # core.tensor.Tensor, duck-typed
        return np.stack([np.asarray(b._value) for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (bool, np.bool_)):
        return np.asarray(batch, dtype=np.bool_)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    return batch


def _flatten_tree(tree):
    """Flatten a collated batch (nested tuple/list/dict) into
    (ndarray leaves, spec). The spec is a picklable skeleton with leaf
    indices where arrays were."""
    leaves = []

    def walk(node):
        if isinstance(node, np.ndarray):
            leaves.append(np.ascontiguousarray(node))
            return ("a", len(leaves) - 1)
        if isinstance(node, (list, tuple)):
            return ("t", type(node).__name__, [walk(x) for x in node])
        if isinstance(node, dict):
            return ("d", [(k, walk(v)) for k, v in node.items()])
        return ("o", node)

    return leaves, walk(tree)


def _unflatten_tree(spec, leaves):
    tag = spec[0]
    if tag == "a":
        return leaves[spec[1]]
    if tag == "t":
        seq = [_unflatten_tree(s, leaves) for s in spec[2]]
        return tuple(seq) if spec[1] == "tuple" else list(seq)
    if tag == "d":
        return {k: _unflatten_tree(s, leaves) for k, s in spec[1]}
    return spec[1]


# --------------------------------------------------------------------------
# process workers: fork-safe context + shared-memory slot transport
# --------------------------------------------------------------------------

def _fork_safe_context(worker_mode):
    """A multiprocessing context that never calls os.fork() in this
    (multithreaded, JAX-owning) process. forkserver preferred: its
    server process is exec'd clean and workers fork from THAT, so
    per-worker startup skips full interpreter boot."""
    import multiprocessing as mp
    methods = mp.get_all_start_methods()
    if worker_mode in ("spawn", "forkserver"):
        if worker_mode not in methods:
            raise ValueError(f"start method {worker_mode!r} unavailable "
                             f"(have {methods})")
        return mp.get_context(worker_mode)
    for m in ("forkserver", "spawn"):
        if m in methods:
            return mp.get_context(m)
    raise RuntimeError("no fork-safe multiprocessing start method available")


def _process_worker_main(ds_bytes, init_bytes, index_q, result_q, wid,
                         num_workers, seed):
    """Worker PROCESS body. Jobs: (seq, indices, slot_name, slot_size,
    mode); None is shutdown. Replies: (seq, slot_name, slot_payload,
    pickled_payload, err) — exactly one payload is non-None on success.

    mode 'arrays': collate to numpy here and write the leaves into the
    shared-memory slot (overflowing batches ship pickled; the parent
    grows the slot). mode 'samples': ship raw samples pickled — the
    parent runs the user's custom collate_fn, preserving its semantics
    and output types exactly.
    """
    # workers produce numpy only; an accidental jax import in dataset
    # code must never initialize an accelerator backend here — pin
    # UNCONDITIONALLY (the parent may export JAX_PLATFORMS=tpu, and a
    # worker contending for the chip is exactly the failure this
    # transport exists to prevent)
    os.environ["JAX_PLATFORMS"] = "cpu"
    global _PROC_WORKER_INFO
    dataset = pickle.loads(ds_bytes)
    worker_init_fn = pickle.loads(init_bytes) if init_bytes else None
    _PROC_WORKER_INFO = WorkerInfo(wid, num_workers, seed=seed,
                                   dataset=dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    from multiprocessing import shared_memory
    open_slots = {}
    try:
        while True:
            job = index_q.get()
            if job is None:
                break
            seq, indices, slot_name, slot_size, mode = job
            try:
                samples = [dataset[i] for i in indices]
                if mode == "samples":
                    result_q.put((seq, slot_name, None, samples, None))
                    continue
                leaves, spec = _flatten_tree(default_collate_numpy(samples))
                total = sum(a.nbytes for a in leaves)
                if slot_name is not None and total <= slot_size:
                    shm = open_slots.get(slot_name)
                    if shm is None:
                        shm = shared_memory.SharedMemory(name=slot_name)
                        open_slots[slot_name] = shm
                    metas, off = [], 0
                    for a in leaves:
                        dst = np.ndarray(a.shape, a.dtype,
                                         buffer=shm.buf, offset=off)
                        dst[...] = a      # zero-copy assembly into the slot
                        metas.append((a.shape, a.dtype.str, off))
                        off += a.nbytes
                    result_q.put((seq, slot_name, (spec, metas), None, None))
                else:
                    # slot too small (or shm off): pickled fallback; the
                    # parent records `total` and grows the slot for the
                    # next acquisition
                    result_q.put((seq, slot_name, None,
                                  (spec, leaves, total), None))
            except Exception as e:   # surface the error in the parent
                result_q.put((seq, slot_name, None, None,
                              f"{type(e).__name__}: {e}"))
    finally:
        for shm in open_slots.values():
            try:
                shm.close()
            except Exception:
                pass


class _SlotPool:
    """Parent-side pool of PREALLOCATED shared-memory batch buffers.

    Slot count == max jobs in flight (backpressure: no free slot, no new
    job). Slots grow geometrically when a batch overflows (the worker
    falls back to pickle for that one batch and reports the needed
    size); growth replaces the slot under a fresh name so a worker's
    stale handle can never alias a recycled buffer.
    """

    def __init__(self, n_slots, slot_bytes=1 << 16):
        from multiprocessing import shared_memory
        self._shm_mod = shared_memory
        self._slots = {}
        self._free = collections.deque()
        for _ in range(n_slots):
            shm = shared_memory.SharedMemory(create=True, size=slot_bytes)
            self._slots[shm.name] = shm
            self._free.append(shm.name)
        self._default_bytes = slot_bytes

    def acquire(self):
        """-> (name, size) or None when every slot is in flight."""
        if not self._free:
            return None
        name = self._free.popleft()
        return name, self._slots[name].size

    def release(self, name, min_bytes=None):
        if name not in self._slots:
            return
        if min_bytes is not None and min_bytes > self._slots[name].size:
            name = self._grow(name, min_bytes)
        self._free.append(name)

    def _grow(self, name, need):
        old = self._slots.pop(name)
        try:
            old.close()
            old.unlink()
        except Exception:
            pass
        size = max(int(need * 1.25), old.size * 2, self._default_bytes)
        shm = self._shm_mod.SharedMemory(create=True, size=size)
        self._slots[shm.name] = shm
        return shm.name

    def view(self, name, metas):
        shm = self._slots[name]
        return [np.ndarray(shape, np.dtype(dt), buffer=shm.buf, offset=off)
                for shape, dt, off in metas]

    def close(self):
        for shm in self._slots.values():
            try:
                shm.close()
                shm.unlink()
            except Exception:
                pass
        self._slots.clear()
        self._free.clear()


def _estimate_batch_bytes(loader, ds_bytes=None):
    """Initial shared-memory slot size: probe ONE sample and scale by
    the batch size, so the first real batches land in the slot instead
    of all paying the pickled-overflow slow path (a 19MB ResNet batch
    against a blind 64KB default would overflow every slot exactly
    once). Slots still grow geometrically on genuine overflow. The
    probe runs against a THROWAWAY pickled-roundtrip copy when
    available: dataset[0] may materialize lazy state (sample pools,
    file handles) that the parent-side object must not keep — the
    parent never serves samples, its workers do."""
    try:
        bs = getattr(loader.batch_sampler, "batch_size", 1) or 1
        dataset = pickle.loads(ds_bytes) if ds_bytes else loader.dataset
        leaves, _ = _flatten_tree(
            default_collate_numpy([dataset[0]]))
        per_sample = sum(a.nbytes for a in leaves)
        return max(1 << 16, int(per_sample * bs * 1.25))
    except Exception:
        return 1 << 16


def dataset_is_picklable(dataset):
    try:
        pickle.dumps(dataset)
        return True
    except Exception:
        return False


# --------------------------------------------------------------------------
# the two worker pools
# --------------------------------------------------------------------------

class _PoolBase:
    """Shared lifecycle: monotonic sequence numbers (unique across
    epochs under persistent_workers) and idempotent shutdown."""

    def __init__(self):
        self._seq = itertools.count()
        self._closed = False

    def next_seq(self):
        return next(self._seq)

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        self._shutdown_impl()

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


class _ThreadPool(_PoolBase):
    """Worker THREADS: fetch + collate in-process. The collate runs the
    loader's real collate_fn, so output types match num_workers=0
    exactly; numpy decode work (slice/copy/stack) releases the GIL."""

    def __init__(self, loader):
        super().__init__()
        self.num_workers = loader.num_workers
        self._dataset = loader.dataset
        self._collate = loader.collate_fn
        self._init_fn = loader.worker_init_fn
        self._index_q = _queue.Queue()
        self.result_q = _queue.Queue()
        self._threads = []
        for wid in range(self.num_workers):
            t = threading.Thread(target=self._worker, args=(wid,),
                                 name=f"paddle-io-worker-{wid}", daemon=True)
            t.start()
            self._threads.append(t)

    def _worker(self, wid):
        # seed=wid matches the process-pool contract, so dataset code
        # keying augmentation off worker_info.seed behaves identically
        # across worker modes
        _THREAD_WORKER_INFO.info = WorkerInfo(wid, self.num_workers,
                                              seed=wid,
                                              dataset=self._dataset)
        if self._init_fn is not None:
            self._init_fn(wid)
        while True:
            job = self._index_q.get()
            if job is None:
                return
            seq, indices = job
            try:
                batch = self._collate([self._dataset[i] for i in indices])
                self.result_q.put((seq, batch, None))
            except Exception as e:
                self.result_q.put((seq, None, f"{type(e).__name__}: {e}"))

    def submit(self, seq, indices):
        self._index_q.put((seq, list(indices)))

    def finalize_batch(self, payload):
        return payload

    def reclaim(self, payload):
        """Drop an unconsumed result (no resources to recycle here)."""

    def workers_alive(self):
        return [t for t in self._threads if t.is_alive()]

    def _shutdown_impl(self):
        for _ in self._threads:
            self._index_q.put(None)
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []


class _ProcessPool(_PoolBase):
    """Worker PROCESSES over a fork-safe start method with shared-memory
    slot transport (see module docstring). `finalize_batch` runs in the
    parent: map views onto the slot, hand them to the device stage, then
    recycle the slot."""

    def __init__(self, loader, mode, ds_bytes=None):
        super().__init__()
        self.num_workers = loader.num_workers
        self._collate = loader.collate_fn
        from .dataloader import default_collate_fn
        self._default_collate = loader.collate_fn is default_collate_fn
        self._use_shm = loader.use_shared_memory and self._default_collate
        self.mode = "arrays" if self._default_collate else "samples"
        n_slots = max(2, self.num_workers * loader.prefetch)
        self.capacity = n_slots
        if ds_bytes is None:
            ds_bytes = pickle.dumps(loader.dataset)
        self._slots = (_SlotPool(n_slots,
                                 slot_bytes=_estimate_batch_bytes(
                                     loader, ds_bytes))
                       if self._use_shm else None)
        ctx = _fork_safe_context(mode)
        self._index_q = ctx.Queue()
        self.result_q = ctx.Queue()
        init_bytes = (pickle.dumps(loader.worker_init_fn)
                      if loader.worker_init_fn is not None else b"")
        self._procs = []
        for wid in range(self.num_workers):
            p = ctx.Process(
                target=_process_worker_main,
                args=(ds_bytes, init_bytes, self._index_q, self.result_q,
                      wid, self.num_workers, wid),
                daemon=True)
            p.start()
            self._procs.append(p)

    def submit(self, seq, indices):
        slot_name, slot_size = None, 0
        if self._slots is not None:
            acq = self._slots.acquire()
            if acq is None:     # caller respects capacity; belt & braces
                raise RuntimeError("no free shared-memory slot")
            slot_name, slot_size = acq
        self._index_q.put((seq, list(indices), slot_name, slot_size,
                           self.mode))

    def finalize_batch(self, payload, to_device=None):
        """payload = (slot_name, slot_payload, pickled_payload). Returns
        the finished host/device batch. `to_device(leaves) -> leaves` is
        applied while the slot is still held (the device stage must
        consume the views before the buffer is recycled)."""
        slot_name, slot_payload, pickled = payload
        if slot_payload is not None:
            spec, metas = slot_payload
            leaves = self._slots.view(slot_name, metas)
            try:
                if to_device is not None:
                    leaves = to_device(leaves)
                else:
                    leaves = [np.array(a) for a in leaves]   # own the data
            finally:
                self._slots.release(slot_name)
            return _unflatten_tree(spec, leaves)
        if slot_name is not None and self._slots is not None:
            # the batch overflowed this slot: grow it for next time
            need = pickled[2] if isinstance(pickled, tuple) \
                and len(pickled) == 3 else None
            self._slots.release(slot_name, min_bytes=need)
        if self.mode == "samples":
            return self._collate(pickled)
        spec, leaves, _ = pickled
        if to_device is not None:
            leaves = to_device(leaves)
        return _unflatten_tree(spec, leaves)

    def reclaim(self, payload):
        """Release the shared-memory slot of an unconsumed result
        (abandoned epoch / worker error) so the next epoch's jobs can
        acquire it — without this, a persistent pool starves."""
        slot_name, slot_payload, pickled = payload
        if slot_name is not None and self._slots is not None:
            need = pickled[2] if isinstance(pickled, tuple) \
                and len(pickled) == 3 else None
            self._slots.release(slot_name, min_bytes=need)

    def workers_alive(self):
        return [p for p in self._procs if p.is_alive()]

    def _shutdown_impl(self):
        for _ in self._procs:
            try:
                self._index_q.put(None)
            except Exception:
                break
        deadline = time.monotonic() + 5
        for p in self._procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
        for p in self._procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
        for q in (self._index_q, self.result_q):
            try:
                q.close()
                q.join_thread()
            except Exception:
                pass
        self._procs = []
        if self._slots is not None:
            self._slots.close()
            self._slots = None


def make_pool(loader):
    """Resolve the loader's worker_mode to a live pool. 'auto' prefers
    threads (zero spawn cost, deadlock-proof); 'process' requires a
    picklable dataset and picks forkserver/spawn. 'fork' is rejected
    outright — that is the deadlock the rebuild removes."""
    mode = getattr(loader, "worker_mode", "auto") or "auto"
    if mode == "fork":
        raise ValueError(
            "worker_mode='fork' is not supported: os.fork() under a "
            "multithreaded JAX runtime deadlocks (BENCH_r04/r05 "
            "RuntimeWarning). Use 'process' (forkserver/spawn), "
            "'thread', or 'auto'.")
    if mode in ("process", "spawn", "forkserver"):
        try:     # pickle ONCE; the bytes ship to the workers as-is
            ds_bytes = pickle.dumps(loader.dataset)
        except Exception as e:
            raise ValueError(
                f"worker_mode={mode!r} needs a picklable dataset "
                "(spawn/forkserver workers receive it by pickle); use "
                f"worker_mode='thread' for closure-captured datasets "
                f"[{type(e).__name__}: {e}]") from e
        return _ProcessPool(loader, mode if mode != "process" else "auto",
                            ds_bytes=ds_bytes)
    if mode in ("auto", "thread"):
        return _ThreadPool(loader)
    raise ValueError(f"unknown worker_mode {mode!r}; expected one of "
                     "'auto', 'thread', 'process', 'spawn', 'forkserver'")


# --------------------------------------------------------------------------
# the multi-worker iterator (sampler order preserved, bounded in-flight)
# --------------------------------------------------------------------------

class MultiWorkerIterator:    # guarded by: none (single active iterator per pool — _invalidate poisons the old one before a new one may submit)
    """Drives a worker pool through one pass of the batch sampler.

    Index feeding has backpressure (jobs in flight <= pool capacity —
    for process pools that is the shared-memory slot count, for thread
    pools num_workers * prefetch), results are REORDERED to sampler
    order regardless of worker completion, and result waits poll worker
    liveness so a killed worker raises instead of hanging. Determinism:
    the sampler runs only in the parent, so for a fixed seed the batch
    stream is identical across num_workers and worker modes."""

    def __init__(self, loader, pool):
        self.loader = loader
        self.pool = pool
        # capture the target placement NOW: DeviceLoader announces it on
        # the loader only around iterator creation, so a later direct
        # iteration (or a second DeviceLoader with a different sharding)
        # can never inherit this iterator's placement
        self._device_sharding = getattr(loader, "device_sharding", None)
        self._stolen = False
        self._jobs = iter(loader.batch_sampler)
        self._n_jobs = len(loader.batch_sampler)
        self._base = None
        self._sent = 0
        self._done = 0
        self._exhausted = False
        self._lost = 0            # error replies consumed off-queue
        self._pending = {}
        self._limit = getattr(pool, "capacity",
                              max(2, pool.num_workers * loader.prefetch))
        self._wait = _WaitTracker()
        self._closed = False
        self._feed()

    def __iter__(self):
        return self

    def _feed(self):
        while not self._exhausted and self._sent - self._done < self._limit:
            try:
                indices = next(self._jobs)
            except StopIteration:
                self._exhausted = True
                return
            seq = self.pool.next_seq()
            if self._base is None:
                self._base = seq
            self.pool.submit(seq, indices)
            self._sent += 1

    def __next__(self):
        from .. import monitor
        if self._stolen:
            raise RuntimeError(
                "this DataLoader iterator was invalidated: a new iterator "
                "was started on the persistent_workers loader (one active "
                "iterator at a time — they share the worker pool)")
        if self._done >= self._n_jobs:
            self.close()
            raise StopIteration
        want = (self._base or 0) + self._done
        t0 = time.perf_counter()
        deadline = self.loader.timeout or None
        while want not in self._pending:
            try:
                seq, *payload = self.pool.result_q.get(
                    timeout=deadline or 5.0)
            except _queue.Empty:
                alive = self.pool.workers_alive()
                if len(alive) < self.pool.num_workers or deadline:
                    self.close()
                    raise RuntimeError(
                        f"DataLoader worker(s) died or timed out waiting "
                        f"{deadline or 5.0}s for batch "
                        f"{want - (self._base or 0)}") from None
                continue
            err = payload[-1]
            if err is not None:
                # the failed job's reply is consumed here: recycle its
                # slot and account it so close()'s drain doesn't wait
                # for a result that already arrived
                self.pool.reclaim(tuple(payload[:-1]))
                self._lost += 1
                self.close()
                raise RuntimeError(f"DataLoader worker failed: {err}")
            self._pending[seq] = payload[:-1]
            # depth counts batches ready beyond the one being awaited
        wait_s = time.perf_counter() - t0
        payload = self._pending.pop(want)
        self._done += 1
        # finalize BEFORE feeding: for process pools, finalize recycles
        # the shared-memory slot the next job needs
        batch = self._finalize(payload)
        self._feed()
        self._wait.fetched(wait_s, len(self._pending))
        monitor.incr("io.batches")
        if self._done >= self._n_jobs and not self.loader.persistent_workers:
            self.close()
        return batch

    def _finalize(self, payload):
        if isinstance(self.pool, _ProcessPool):
            if self.pool.mode == "samples":
                # custom collate_fn ran in the parent: its output types
                # must pass through untouched (exactly what num_workers
                # =0 and thread modes yield)
                return self.pool.finalize_batch(tuple(payload))
            out = self.pool.finalize_batch(
                tuple(payload),
                to_device=self.loader._leaf_transfer(self._device_sharding))
            return self.loader._wrap_leaves(out)
        return payload[0]

    def _invalidate(self):
        """Called when a NEW iterator is started on the persistent-
        workers loader this iterator was feeding: drain the in-flight
        jobs (their slots must recycle before the new iterator submits)
        and poison this one — two live iterators over the shared pool
        would steal each other's results and deadlock."""
        self.close()
        self._stolen = True

    def close(self):
        if self._closed:
            return
        self._closed = True
        if not self.loader.persistent_workers:
            self.pool.shutdown()
            if getattr(self.loader, "_pool", None) is self.pool:
                self.loader._pool = None
            return
        # persistent pool outlives this iterator: every in-flight job's
        # result must be drained and its shared-memory slot reclaimed,
        # or the next epoch's submits starve on slot acquisition (and
        # stale results poison the next iterator's reorder buffer)
        outstanding = (self._sent - self._done - self._lost
                       - len(self._pending))
        for payload in self._pending.values():
            self.pool.reclaim(tuple(payload))
        self._pending.clear()
        deadline = time.monotonic() + 10
        while outstanding > 0 and time.monotonic() < deadline:
            try:
                _seq, *payload = self.pool.result_q.get(timeout=0.5)
            except _queue.Empty:
                if len(self.pool.workers_alive()) < self.pool.num_workers:
                    break
                continue
            self.pool.reclaim(tuple(payload[:-1]))
            outstanding -= 1
        if outstanding > 0:
            # could not drain cleanly (dead worker / lost job): the pool
            # is poisoned — tear it down so the next epoch rebuilds
            self.pool.shutdown()
            if getattr(self.loader, "_pool", None) is self.pool:
                self.loader._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# prefetch-to-device: the double-buffered device iterator
# --------------------------------------------------------------------------

def _resolve_sharding(sharding):
    """None | jax.sharding.Sharding | Mesh | callable(arr)->Sharding
    -> callable(arr)->Sharding-or-None."""
    if sharding is None:
        return lambda arr: None
    if callable(sharding) and not hasattr(sharding, "spec") \
            and type(sharding).__name__ != "Mesh":
        return sharding
    if type(sharding).__name__ == "Mesh":
        mesh = sharding

        def per_leaf(arr):
            from ..distributed import env
            return env.trim_batch_sharding(arr, env.batch_sharding(mesh),
                                           mesh)
        return per_leaf
    sh = sharding

    def fixed(arr):
        from ..distributed import env
        return env.trim_batch_sharding(arr, sh, getattr(sh, "mesh", None))
    return fixed


def _leaf_put(sharding):
    """-> put(value) -> device jax.Array for one array leaf, honoring
    the resolved per-leaf sharding and skipping the transfer entirely
    when the value is already equivalently placed (the no-redundant-h2d
    contract ShardedTrainStep relies on)."""
    import jax
    per_leaf = _resolve_sharding(sharding)

    def put(v):
        sh = per_leaf(v)
        if isinstance(v, jax.Array):
            cur = getattr(v, "sharding", None)
            if sh is None:
                return v
            try:
                if cur is not None and cur.is_equivalent_to(sh, v.ndim):
                    return v
            except Exception:
                pass
        return jax.device_put(v, sh) if sh is not None else jax.device_put(v)
    return put


def device_put_batch(batch, sharding=None):
    """Move every array leaf of a (possibly nested) host batch onto the
    device(s): ``jax.device_put`` with the resolved per-leaf Sharding —
    each dp shard lands directly on its device, no host-side gather or
    re-split. Tensor leaves come back as Tensors on fresh device values.
    Blocks until the transfers complete so callers may recycle the host
    buffers (shared-memory slots) immediately after return."""
    import jax
    from ..core.tensor import Tensor
    put = _leaf_put(sharding)

    def to_dev(x):
        if isinstance(x, Tensor):
            return Tensor(put(x._value), stop_gradient=x.stop_gradient)
        if isinstance(x, (np.ndarray, jax.Array)):
            return put(x)
        return x

    moved = jax.tree_util.tree_map(
        to_dev, batch, is_leaf=lambda x: isinstance(x, Tensor))
    arrs = [x._value if isinstance(x, Tensor) else x
            for x in jax.tree_util.tree_leaves(
                moved, is_leaf=lambda x: isinstance(x, Tensor))
            if isinstance(x, Tensor) or isinstance(x, jax.Array)]
    if arrs:
        jax.block_until_ready(arrs)
    return moved


class DeviceLoader:
    """Double-buffered device iterator over any host-batch iterable.

    A background stage thread pulls host batches and dispatches their
    H2D transfer (``jax.device_put`` with an explicit per-leaf Sharding
    when given), keeping up to ``size`` device-resident batches queued —
    step N's compute overlaps batch N+1's transfer. ``__next__`` yields
    batches whose array leaves are already jax Arrays placed per the
    sharding (TrainStep passes them through untouched;
    ShardedTrainStep's shard_batch recognizes the placement and skips
    its own device_put), and records input_wait_ms / queue depth /
    input-bound fraction into the monitor gauges and the telemetry
    step records.

    sharding: None (default device) | a jax Sharding (trimmed per leaf
    rank/divisibility) | a Mesh (dp/sp batch sharding from
    distributed.env) | callable(ndarray) -> Sharding.
    """

    def __init__(self, loader, sharding=None, size=2):
        self.loader = loader
        self.sharding = sharding
        self.size = max(1, int(size))

    def __len__(self):
        return len(self.loader)

    def __iter__(self):
        # tell a wrapped DataLoader the target placement BEFORE its
        # iterator spins up: process-pool finalize then device_puts the
        # shared-memory views straight to the right devices and the
        # stage's device_put_batch recognizes the placement (no second
        # reshard hop)
        if hasattr(self.loader, "device_sharding"):
            # scoped to iterator creation: MultiWorkerIterator captures
            # the placement in __init__, so the attribute resets before
            # anyone else iterates the loader
            self.loader.device_sharding = self.sharding
            try:
                host_iter = iter(self.loader)
            finally:
                self.loader.device_sharding = None
        else:
            host_iter = iter(self.loader)
        return _DeviceIterator(self, host_iter)


def _device_stage_main(host_iter, q, stop, sharding, errbox, sentinel):
    """Stage-thread body, deliberately a MODULE function: the thread
    must hold no reference to the _DeviceIterator, or an abandoned
    iterator (consumer broke out without close()) could never be
    garbage-collected and its finalizer — the only thing that stops
    this loop — would never run."""
    _INTERIOR.on = True     # host-iterator waits in this thread are
    # pipeline-internal, not the consumer's input wait
    try:
        for batch in host_iter:
            if stop.is_set():
                break
            batch = device_put_batch(batch, sharding)
            while not stop.is_set():
                try:
                    q.put(batch, timeout=0.25)
                    break
                except _queue.Full:
                    continue
    except BaseException as e:          # surfaced on the consumer side
        errbox.append(e)
    finally:
        while not stop.is_set():
            try:
                q.put(sentinel, timeout=0.25)
                break
            except _queue.Full:
                continue


class _DeviceIterator:
    _SENTINEL = object()

    def __init__(self, dl, host_iter):
        self._q = _queue.Queue(maxsize=dl.size)
        self._errbox = []
        self._finished = False
        self._stop = threading.Event()
        self._wait = _WaitTracker()
        self._thread = threading.Thread(
            target=_device_stage_main,
            args=(host_iter, self._q, self._stop, dl.sharding,
                  self._errbox, self._SENTINEL),
            name="paddle-io-device-stage", daemon=True)
        self._thread.start()
        weakref.finalize(self, self._stop.set)

    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration    # repeated next() must not block
        t0 = time.perf_counter()
        while True:
            try:
                item = self._q.get(timeout=0.25)
                break
            except _queue.Empty:
                # closed or stage thread gone with nothing queued: the
                # sentinel will never come — finish instead of hanging
                if self._stop.is_set() or not self._thread.is_alive():
                    self._finished = True
                    if self._errbox:
                        raise self._errbox.pop(0)
                    raise StopIteration from None
        wait_s = time.perf_counter() - t0
        if item is self._SENTINEL:
            self._finished = True
            self._stop.set()
            if self._errbox:
                raise self._errbox.pop(0)
            raise StopIteration
        self._wait.fetched(wait_s, self._q.qsize())
        return item

    def close(self):
        self._stop.set()
        # Drain-and-join until the stage thread is really gone. A single
        # drain raced the stage thread: it could already be inside
        # `q.put(batch, timeout=0.25)` when stop was set, so its put
        # succeeded AFTER our sweep and a device-resident batch stayed
        # pinned in the queue for the iterator's remaining lifetime.
        # Draining in a loop keeps the queue unblocked until the thread
        # observes stop and exits; the final sweep catches anything the
        # last put landed.
        t = self._thread
        while t.is_alive():
            self._drain()
            t.join(timeout=0.05)
        self._drain()

    def _drain(self):
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass


def prefetch_to_device(loader, sharding=None, size=2):
    """Wrap `loader` (a DataLoader or any iterable of host batches) in a
    DeviceLoader: device-resident, double-buffered, wait-instrumented.
    The tf.data ``prefetch_to_device`` analog."""
    return DeviceLoader(loader, sharding=sharding, size=size)
