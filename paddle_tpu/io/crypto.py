"""Encrypted checkpoint save/load.

Parity target: the reference's crypto save path
(`paddle/fluid/framework/io/crypto/cipher.cc` AESCipher +
`python/paddle/fluid/core` CipherUtils — AES-GCM over serialized
programs/params). This environment ships no AES implementation (no
`cryptography` package), so the cipher is built from hashlib primitives:
HMAC-SHA256 in counter mode as the keystream (a standard PRF-CTR stream
cipher) with encrypt-then-MAC HMAC-SHA256 integrity — authenticated
encryption with the same operational contract (wrong key/tampered file
=> hard failure), not AES-compatible bytes.
"""
import hashlib
import hmac
import os
import pickle
import struct

from .serialization import _to_saveable, _from_saved

__all__ = ["encrypt_save", "decrypt_load", "CryptoError"]

_MAGIC = b"PTPUENC1"


class CryptoError(RuntimeError):
    pass


def _derive(key, salt, label):
    if isinstance(key, str):
        key = key.encode()
    return hashlib.pbkdf2_hmac("sha256", key, salt + label, 100_000)


def _keystream_xor(data, key, nonce):
    import numpy as np
    n = len(data)
    block = 32
    n_blocks = (n + block - 1) // block
    # generate the keystream in one pass, XOR as numpy uint8 vectors —
    # a byte-at-a-time python loop is minutes per GB of checkpoint
    ks = bytearray(n_blocks * block)
    for i in range(n_blocks):
        ks[i * block:(i + 1) * block] = hmac.new(
            key, nonce + struct.pack("<Q", i), hashlib.sha256).digest()
    a = np.frombuffer(data, np.uint8)
    b = np.frombuffer(bytes(ks[:n]), np.uint8)
    return (a ^ b).tobytes()


def encrypt_save(obj, path, key, protocol=4):
    """Serialize `obj` (any paddle save-able pytree) and write it
    encrypted+authenticated to `path`."""
    payload = pickle.dumps(_to_saveable(obj), protocol=protocol)
    salt = os.urandom(16)
    nonce = os.urandom(16)
    ekey = _derive(key, salt, b"enc")
    mkey = _derive(key, salt, b"mac")
    ct = _keystream_xor(payload, ekey, nonce)
    body = _MAGIC + salt + nonce + ct
    tag = hmac.new(mkey, body, hashlib.sha256).digest()
    with open(path, "wb") as f:
        f.write(body + tag)


def decrypt_load(path, key, return_numpy=False):
    """Load a file written by encrypt_save. Raises CryptoError on a
    wrong key, truncation, or any tampering (tag verified before any
    pickle parsing touches attacker-controllable bytes)."""
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < len(_MAGIC) + 16 + 16 + 32 or \
            not blob.startswith(_MAGIC):
        raise CryptoError(f"{path}: not a paddle_tpu encrypted file")
    body, tag = blob[:-32], blob[-32:]
    salt = body[len(_MAGIC):len(_MAGIC) + 16]
    nonce = body[len(_MAGIC) + 16:len(_MAGIC) + 32]
    ct = body[len(_MAGIC) + 32:]
    mkey = _derive(key, salt, b"mac")
    if not hmac.compare_digest(
            hmac.new(mkey, body, hashlib.sha256).digest(), tag):
        raise CryptoError(
            f"{path}: authentication failed (wrong key or corrupted "
            "file)")
    ekey = _derive(key, salt, b"enc")
    payload = _keystream_xor(ct, ekey, nonce)
    return _from_saved(pickle.loads(payload), return_numpy)
