"""Unique-name generator (reference `python/paddle/utils/unique_name.py`,
backing `fluid/unique_name.py`): thread-shared counter per prefix, with
`guard` providing a fresh namespace for program-building blocks."""
import contextlib
import threading

__all__ = ["generate", "switch", "guard"]


class _Generator:
    def __init__(self):
        self.ids = {}
        self.lock = threading.Lock()

    def __call__(self, key):
        with self.lock:
            n = self.ids.get(key, 0)
            self.ids[key] = n + 1
        return f"{key}_{n}"


_generator = _Generator()


def generate(key):
    return _generator(key)


def switch(new_generator=None, new_para_name_checker=None):
    """Install (or reset) the namespace; returns the previous one.
    new_para_name_checker is accepted for reference signature parity
    (`fluid/unique_name.py` switch) — this build has no dygraph
    param-name checker to swap, names are unique by construction."""
    global _generator
    old = _generator
    _generator = new_generator or _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
