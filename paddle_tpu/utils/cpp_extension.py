"""JIT C++ extension loading — the custom-op build path.

Parity target: `python/paddle/utils/cpp_extension/cpp_extension.py:1`
(CppExtension/CUDAExtension + the JIT `load()` API over a hidden
setuptools build). TPU-native redesign: device compute belongs in
Pallas/jax (write a function and register it with `autograd.PyLayer` —
no C++ needed for kernels), so the C++ extension path targets what
genuinely needs native code on a TPU host: data loaders, tokenizers,
feature extraction, host-side services. `load()` compiles sources with
g++ into a shared library and binds `extern "C"` functions via ctypes —
the same on-demand toolchain the in-tree runtimes use (`csrc/pskv.cc`,
`csrc/ptio.cc`, `csrc/kvstore.cc`); there is no pybind11 in the image.
"""
import ctypes
import hashlib
import os
import subprocess
import threading

__all__ = ["load", "CppExtension", "CUDAExtension", "setup",
           "get_build_directory"]

_cache = {}
_cache_lock = threading.Lock()

_CTYPE = {
    "void": None,
    "int": ctypes.c_int,
    "int32": ctypes.c_int32,
    "int64": ctypes.c_int64,
    "float": ctypes.c_float,
    "double": ctypes.c_double,
    "char*": ctypes.c_char_p,
    "str": ctypes.c_char_p,
    "void*": ctypes.c_void_p,
    "float*": ctypes.POINTER(ctypes.c_float),
    "double*": ctypes.POINTER(ctypes.c_double),
    "int32*": ctypes.POINTER(ctypes.c_int32),
    "int64*": ctypes.POINTER(ctypes.c_int64),
}


def get_build_directory(verbose=False):
    d = os.environ.get(
        "PADDLE_EXTENSION_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """Source-set descriptor (reference CppExtension signature)."""

    def __init__(self, sources, extra_compile_args=None,
                 extra_link_args=None, include_dirs=None):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])
        self.extra_link_args = list(extra_link_args or [])
        self.include_dirs = list(include_dirs or [])


class _Extension:
    """Loaded library: declared functions become attributes."""

    def __init__(self, name, lib, so_path):
        self._name = name
        self._lib = lib
        self.so_path = so_path

    def __getattr__(self, item):
        return getattr(self._lib, item)

    def __repr__(self):
        return f"<paddle_tpu extension {self._name} at {self.so_path}>"


def _parse_sig(sig):
    """'double sum_sq(float*, int64)' -> (name, restype, argtypes)."""
    ret, _, rest = sig.strip().partition(" ")
    name, _, args = rest.partition("(")
    args = args.rstrip(") ").strip()
    argtypes = []
    if args and args != "void":
        for a in args.split(","):
            a = a.strip()
            if a not in _CTYPE:
                raise ValueError(
                    f"unsupported ctypes arg {a!r} in signature {sig!r}; "
                    f"one of {sorted(_CTYPE)}")
            argtypes.append(_CTYPE[a])
    if ret not in _CTYPE:
        raise ValueError(f"unsupported return type {ret!r} in {sig!r}")
    return name.strip(), _CTYPE[ret], argtypes


def load(name, sources=None, extra_cxx_cflags=None,
         extra_cuda_cflags=None, extra_ldflags=None,
         extra_include_paths=None, build_directory=None, verbose=False,
         extension=None, functions=None, extra_cflags=None,
         include_dirs=None):
    """Compile C++ `sources` and return the bound library. Positional
    layout follows the reference `cpp_extension.load`
    (`utils/cpp_extension/cpp_extension.py:727`); `extension`,
    `functions`, `extra_cflags` and `include_dirs` are this backend's
    extensions (ctypes binding needs declared C signatures).

    extra_cxx_cflags/extra_include_paths merge with extra_cflags/
    include_dirs; extra_cuda_cflags raises — there is no CUDA compile
    on this backend (write device kernels in Pallas).

    functions: list of C signatures to declare, e.g.
        ["double dotf(float*, float*, int64)", "void scale(float*, int64,
        float)"]
    Exported symbols must be `extern "C"`. Recompiles only when any
    source is newer than the cached .so (hash of name+sources).
    """
    if extra_cuda_cflags:
        raise NotImplementedError(
            "extra_cuda_cflags: no CUDA compile exists on this backend; "
            "device kernels are Pallas (see paddle_tpu/ops/pallas_*.py)")
    extra_cflags = (extra_cflags or []) + list(extra_cxx_cflags or [])
    include_dirs = (include_dirs or []) + list(extra_include_paths or [])
    if extension is not None:
        sources = extension.sources
        extra_cflags = (extra_cflags or []) + extension.extra_compile_args
        extra_ldflags = (extra_ldflags or []) + extension.extra_link_args
        include_dirs = (include_dirs or []) + extension.include_dirs
    if not sources:
        raise ValueError("load() needs sources (or extension=)")
    sources = [os.path.abspath(s) for s in sources]
    for s in sources:
        if not os.path.exists(s):
            raise FileNotFoundError(s)

    key = hashlib.sha1(
        (name + "\0" + "\0".join(sources)).encode()).hexdigest()[:12]
    out_dir = build_directory or get_build_directory()
    so = os.path.join(out_dir, f"{name}-{key}.so")

    with _cache_lock:
        cached = _cache.get(so)
        if cached is None:
            stale = (not os.path.exists(so) or any(
                os.path.getmtime(s) > os.path.getmtime(so)
                for s in sources))
            if stale:
                cmd = (["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                        "-pthread"]
                       + [f"-I{d}" for d in (include_dirs or [])]
                       + (extra_cflags or []) + sources
                       + ["-o", so + ".tmp"] + (extra_ldflags or []))
                if verbose:
                    print("[paddle_tpu.cpp_extension]", " ".join(cmd))
                try:
                    subprocess.run(cmd, check=True, capture_output=True,
                                   text=True)
                except subprocess.CalledProcessError as e:
                    raise RuntimeError(
                        f"extension {name!r} failed to compile:\n"
                        f"{e.stderr}") from None
                os.replace(so + ".tmp", so)
            cached = _Extension(name, ctypes.CDLL(so), so)
            _cache[so] = cached

    if functions:
        for sig in functions:
            fname, restype, argtypes = _parse_sig(sig)
            fn = getattr(cached._lib, fname)
            fn.restype = restype
            fn.argtypes = argtypes
    return cached


class CUDAExtension(CppExtension):
    """Reference CUDAExtension signature. CUDA sources have no TPU
    meaning — this raises at BUILD time with the migration route (the
    TPU path for custom device kernels is Pallas; host-side C++ stays
    CppExtension) rather than pretending .cu files compile here."""

    def __init__(self, sources, *args, **kwargs):
        cu = [s for s in sources if str(s).endswith((".cu", ".cuh"))]
        if cu:
            raise NotImplementedError(
                f"CUDAExtension: CUDA sources {cu} cannot build for TPU. "
                "Write device kernels in Pallas "
                "(paddle_tpu/ops/pallas_*.py is the pattern) and keep "
                "host-side C++ in CppExtension.")
        super().__init__(sources, *args, **kwargs)


def setup(name=None, ext_modules=None, **kwargs):
    """Reference `cpp_extension.setup` analog: build each extension's
    sources now (same g++ + content-keyed cache as `load`) and install
    an importable module handle under the caller-visible name. The
    reference delegates to setuptools; here building IS the install,
    which keeps the zero-setup `import` contract."""
    import sys
    import types
    exts = ext_modules or []
    if not isinstance(exts, (list, tuple)):
        exts = [exts]
    if name is not None and len(exts) > 1:
        raise ValueError(
            "setup(name=..., ext_modules=[...]) with more than one "
            "extension is ambiguous here (every module would take the "
            "same name); call setup once per extension")
    mods = []
    for i, ext in enumerate(exts):
        ext_name = name or f"paddle_tpu_ext_{i}"
        handle = load(ext_name, extension=ext if isinstance(
            ext, CppExtension) else CppExtension(list(ext)))
        mod = types.ModuleType(ext_name)
        mod.__dict__["_ext"] = handle
        mod.__getattr__ = lambda item, _h=handle: getattr(_h, item)
        sys.modules[ext_name] = mod
        mods.append(mod)
    return mods
