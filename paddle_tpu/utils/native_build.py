"""Native library resolution/build shared by the ctypes runtimes.

Resolution order (reference analog: the prebuilt-vs-source duality of
`cmake/operators.cmake` op libraries):
  1. `paddle_tpu/_native/lib<name>.so` — prebuilt by `setup.py` /
     `cmake -S csrc` for installed packages;
  2. `csrc/build/lib<name>.so` next to the source checkout — built (and
     mtime-rebuilt) on demand with g++, so a dev tree needs no build step.
"""
import os
import subprocess
import threading

_lock = threading.Lock()

_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-pthread"]


def repo_csrc():
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "csrc")


def native_lib_path(name, source=None, extra_flags=()):
    """Absolute path to lib<name>.so, building from csrc on demand.
    `source` overrides the default `<name>.cc`; `extra_flags` appends
    compile/link flags (e.g. -ldl, -I... for the PJRT-based runner)."""
    pkg_native = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "_native", f"lib{name}.so")
    src = os.path.join(repo_csrc(), source or f"{name}.cc")
    if os.path.exists(pkg_native) and (
            not os.path.exists(src) or
            os.path.getmtime(pkg_native) >= os.path.getmtime(src)):
        return pkg_native
    if not os.path.exists(src):
        raise FileNotFoundError(
            f"native library {name!r}: neither a prebuilt "
            f"{pkg_native} nor source {src} exists")
    out_dir = os.path.join(repo_csrc(), "build")
    so = os.path.join(out_dir, f"lib{name}.so")
    with _lock:
        if (not os.path.exists(so) or
                os.path.getmtime(so) < os.path.getmtime(src)):
            os.makedirs(out_dir, exist_ok=True)
            inc = os.path.join(repo_csrc(), "third_party")
            subprocess.run(["g++", *_FLAGS, f"-I{inc}", src,
                            "-o", so + ".tmp", *extra_flags],
                           check=True, capture_output=True)
            os.replace(so + ".tmp", so)
    return so
