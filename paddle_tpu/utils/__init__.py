"""paddle_tpu.utils — mirrors `python/paddle/utils/`."""
from . import cpp_extension  # noqa: F401
