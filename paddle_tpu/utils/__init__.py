"""paddle_tpu.utils — mirrors `python/paddle/utils/`."""
from . import cpp_extension  # noqa: F401
from . import unique_name  # noqa: F401


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference
    `python/paddle/utils/deprecated.py`): the warning is forced visible
    (library DeprecationWarnings are filtered out by default) and fires
    once per function."""
    import functools
    import warnings

    def decorate(fn):
        msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f"; use {update_to} instead"
        if reason:
            msg += f" ({reason})"
        if level == 2:
            @functools.wraps(fn)
            def dead(*a, **k):
                raise RuntimeError(msg)
            return dead

        warned = []

        @functools.wraps(fn)
        def wrapper(*a, **k):
            if not warned:
                warned.append(True)
                with warnings.catch_warnings():
                    warnings.simplefilter("always", DeprecationWarning)
                    warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*a, **k)
        return wrapper
    return decorate


def try_import(module_name, err_msg=None):
    """Import a soft dependency with a clear install hint (reference
    `python/paddle/utils/lazy_import.py` try_import)."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"module {module_name!r} is required for this "
            "feature but is not installed (installs are disabled in this "
            "environment; gate the caller instead)")


def run_check():
    """Install sanity check (reference `paddle.utils.install_check
    .run_check`): run a tiny compiled computation on the default backend
    and report."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    out = jax.jit(lambda a, b: (a @ b).sum())(
        jnp.ones((8, 8), jnp.float32), jnp.ones((8, 8), jnp.float32))
    np.testing.assert_allclose(float(out), 512.0)
    n = jax.device_count()
    backend = jax.default_backend()
    print(f"paddle_tpu is installed successfully! "
          f"backend={backend}, {n} device(s) visible.")
    return True


def require_version(min_version, max_version=None):
    """Reference `utils/install_check.py require_version`: assert the
    installed framework version is in range."""
    from .. import __version__

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3])
    cur = parse(__version__)
    if parse(min_version) > cur:
        raise RuntimeError(
            f"paddle_tpu>={min_version} required, found {__version__}")
    if max_version is not None and parse(max_version) < cur:
        raise RuntimeError(
            f"paddle_tpu<={max_version} required, found {__version__}")
