"""Probability distributions (reference `python/paddle/distribution.py`:
Distribution base, Uniform:169, Normal:391, Categorical:641).

TPU-native: sampling draws from the global Generator's split keys (so
`paddle.seed` governs reproducibility and sampling is traceable under
jit), densities are pure jnp expressions XLA fuses.
"""
import numpy as np
import jax
import jax.numpy as jnp

from .core.tensor import Tensor, apply
from .core.random import default_generator

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32)


class Distribution:
    """Abstract base (reference `distribution.py:42`)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (reference `distribution.py:169`)."""

    def __init__(self, low, high, name=None):
        self.low = Tensor(_val(low))
        self.high = Tensor(_val(high))

    def sample(self, shape, seed=0):
        key = default_generator().split()
        lo, hi = self.low._value, self.high._value
        bshape = tuple(shape) + jnp.broadcast_shapes(lo.shape, hi.shape)
        u = jax.random.uniform(key, bshape, jnp.float32)
        return Tensor(lo + u * (hi - lo))

    def log_prob(self, value):
        def fn(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            lp = -jnp.log(hi - lo)
            return jnp.where(inside, lp, -jnp.inf)
        return apply(fn, value if isinstance(value, Tensor)
                     else Tensor(_val(value)), self.low, self.high)

    def entropy(self):
        return apply(lambda lo, hi: jnp.log(hi - lo), self.low, self.high)


class Normal(Distribution):
    """N(loc, scale) (reference `distribution.py:391`)."""

    def __init__(self, loc, scale, name=None):
        self.loc = Tensor(_val(loc))
        self.scale = Tensor(_val(scale))

    def sample(self, shape, seed=0):
        key = default_generator().split()
        mu, sd = self.loc._value, self.scale._value
        bshape = tuple(shape) + jnp.broadcast_shapes(mu.shape, sd.shape)
        return Tensor(mu + sd * jax.random.normal(key, bshape, jnp.float32))

    def log_prob(self, value):
        def fn(v, mu, sd):
            var = sd * sd
            return (-((v - mu) ** 2) / (2 * var)
                    - jnp.log(sd) - 0.5 * jnp.log(2 * jnp.pi))
        return apply(fn, value if isinstance(value, Tensor)
                     else Tensor(_val(value)), self.loc, self.scale)

    def entropy(self):
        return apply(
            lambda mu, sd: jnp.broadcast_to(
                0.5 + 0.5 * jnp.log(2 * jnp.pi) + jnp.log(sd),
                jnp.broadcast_shapes(mu.shape, sd.shape)),
            self.loc, self.scale)

    def kl_divergence(self, other):
        """KL(self || other), both Normal (reference `:596`)."""
        def fn(mu1, sd1, mu2, sd2):
            var_ratio = (sd1 / sd2) ** 2
            t1 = ((mu1 - mu2) / sd2) ** 2
            return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
        return apply(fn, self.loc, self.scale, other.loc, other.scale)


class Categorical(Distribution):
    """Unnormalized-logits categorical (reference `distribution.py:641`;
    NOTE the reference treats `logits` as unnormalized PROBABILITIES,
    not log-probabilities — parity kept)."""

    def __init__(self, logits, name=None):
        self.logits = logits if isinstance(logits, Tensor) \
            else Tensor(_val(logits))

    def _p(self):
        def fn(l):
            return l / jnp.sum(l, axis=-1, keepdims=True)
        return apply(fn, self.logits)

    def sample(self, shape):
        key = default_generator().split()
        p = self._p()._value
        # batched logits: sample over the last axis per batch element
        # (reference returns shape + batch_shape)
        out_shape = tuple(shape) + p.shape[:-1]
        idx = jax.random.categorical(key, jnp.log(p + 1e-12), axis=-1,
                                     shape=out_shape)
        return Tensor(idx)

    def probs(self, value):
        p = self._p()

        def fn(pv, idx):
            return jnp.take(pv, idx.astype(jnp.int32), axis=-1)
        return apply(fn, p, value if isinstance(value, Tensor)
                     else Tensor(jnp.asarray(value)))

    def log_prob(self, value):
        return apply(jnp.log, self.probs(value))

    def entropy(self):
        return apply(
            lambda p: -jnp.sum(p * jnp.log(p + 1e-12), axis=-1), self._p())

    def kl_divergence(self, other):
        return apply(
            lambda p, q: jnp.sum(p * (jnp.log(p + 1e-12)
                                      - jnp.log(q + 1e-12)), axis=-1),
            self._p(), other._p())
