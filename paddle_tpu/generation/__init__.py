"""Autoregressive generation: KV-cache decoding, sampling, beam search.

TPU-native replacement for the reference's decoding stack
(`python/paddle/fluid/layers/rnn.py:866` BeamSearchDecoder, `:1583`
dynamic_decode, `paddle/fluid/operators/beam_search_op.cc:1`): instead of a
host-driven op loop growing LoD tensors step by step, the WHOLE decode —
prefill, `lax.while_loop` token loop, sampling/beam bookkeeping — compiles
into one XLA program over fixed-shape buffers. Per-token work is a single
device dispatch with no host round-trip.

Entry points:
- `run_generate(model, ids, ...)` — greedy / top-k / top-p sampling / beam
  search for models with the (logits, caches) incremental-forward protocol
  (see GPTForPretraining.forward).
- `dynamic_decode(decoder, ...)` + `BeamSearchDecoder` — the reference's
  cell-level decoding API for RNN-style models (eager loop; inference-time
  post-processing path).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.tensor import Tensor
from ..core.random import default_generator
from ..jit import bind_tensors

__all__ = ["run_generate", "dynamic_decode", "BeamSearchDecoder"]

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# token selection
# ---------------------------------------------------------------------------

def _apply_top_k(logits, k):
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG_INF, logits)


def _apply_top_p(logits, p):
    sort_idx = jnp.argsort(-logits, axis=-1)
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < p  # always keeps the top token
    masked = jnp.where(keep, sorted_logits, _NEG_INF)
    inv = jnp.argsort(sort_idx, axis=-1)
    return jnp.take_along_axis(masked, inv, axis=-1)


def _make_selector(decode_strategy, top_k, top_p, temperature):
    def select(logits, key):
        lg = logits.astype(jnp.float32)
        if temperature != 1.0:
            lg = lg / temperature
        if decode_strategy == "greedy":
            tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        else:
            if top_k and top_k > 0:
                lg = _apply_top_k(lg, int(top_k))
            if top_p is not None and top_p < 1.0:
                lg = _apply_top_p(lg, float(top_p))
            tok = jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tok_logp = jnp.take_along_axis(logp, tok[:, None], axis=-1)[:, 0]
        return tok, tok_logp
    return select


# ---------------------------------------------------------------------------
# model plumbing
# ---------------------------------------------------------------------------

def _model_core(model):
    core = getattr(model, "gpt", None)
    if core is None or not hasattr(core, "init_cache"):
        core = model
    if not hasattr(core, "init_cache"):
        raise TypeError(
            "generate() needs a model exposing init_cache(batch, max_len) "
            "and forward(ids, caches=, offset=) -> (logits, caches)")
    return core


def _fwd(model, ids_vals, cache_vals, off_val):
    """One incremental forward on raw values (called inside jit traces)."""
    with autograd.fresh_tape():
        caches = [(Tensor(k), Tensor(v)) for k, v in cache_vals]
        logits, new_caches = model(
            Tensor(ids_vals), caches=caches,
            offset=Tensor(jnp.asarray(off_val, jnp.int32)))
        return (logits._value,
                [(k._value, v._value) for k, v in new_caches])


def _cast_params(param_vals, dtype):
    """Inside-the-jit dtype cast (traced once; XLA hoists it out of the
    decode while_loop, so the loop reads bf16 weights — the whole
    bandwidth win). Int/bool buffers keep their dtype."""
    if dtype is None:
        return param_vals
    cdt = jnp.dtype(dtype)
    return [v.astype(cdt) if jnp.issubdtype(v.dtype, jnp.floating) else v
            for v in param_vals]


# ---------------------------------------------------------------------------
# sampling / greedy loop
# ---------------------------------------------------------------------------

def _build_sample_fn(model, params, s0, max_new, select, eos_token_id,
                     pad_token_id, dtype=None):
    core = _model_core(model)
    eos = -1 if eos_token_id is None else int(eos_token_id)

    def gen(param_vals, ids, rng):
        param_vals = _cast_params(param_vals, dtype)
        with autograd.fresh_tape(), autograd.no_grad(), \
                bind_tensors(params, param_vals):
            b = ids.shape[0]
            total = s0 + max_new
            caches = core.init_cache(b, total)
            cache_vals = [(k._value, v._value) for k, v in caches]
            logits, cache_vals = _fwd(model, ids, cache_vals, 0)
            last = logits[:, -1]
            out = jnp.concatenate(
                [ids, jnp.full((b, max_new), pad_token_id, ids.dtype)], 1)

            def cond(c):
                _, cur, done = c[0], c[1], c[2]
                return jnp.logical_and(cur < total,
                                       jnp.logical_not(jnp.all(done)))

            def body(c):
                out, cur, done, last, cache_vals, rng, score = c
                rng, sub = jax.random.split(rng)
                tok, tok_logp = select(last, sub)
                tok = jnp.where(done, pad_token_id, tok)
                score = score + jnp.where(done, 0.0, tok_logp)
                done = jnp.logical_or(done, tok == eos)
                out = jax.lax.dynamic_update_slice(
                    out, tok[:, None].astype(out.dtype), (0, cur))
                logits, cache_vals = _fwd(model, tok[:, None], cache_vals,
                                          cur)
                return (out, cur + 1, done, logits[:, -1], cache_vals, rng,
                        score)

            init = (out, jnp.asarray(s0, jnp.int32),
                    jnp.zeros((b,), jnp.bool_), last, cache_vals, rng,
                    jnp.zeros((b,), jnp.float32))
            out, _, _, _, _, _, score = jax.lax.while_loop(cond, body, init)
            return out, score

    return jax.jit(gen)


# ---------------------------------------------------------------------------
# beam search loop
# ---------------------------------------------------------------------------

def _build_beam_fn(model, params, s0, max_new, num_beams, length_penalty,
                   eos_token_id, pad_token_id, temperature, dtype=None):
    core = _model_core(model)
    eos = -1 if eos_token_id is None else int(eos_token_id)
    nb = int(num_beams)

    def penalize(scores, lengths):
        if length_penalty == 0.0:
            return scores
        # GNMT length penalty ((5+len)/6)^alpha (Wu et al. 2016)
        lp = jnp.power((5.0 + lengths.astype(jnp.float32)) / 6.0,
                       length_penalty)
        return scores / lp

    def gen(param_vals, ids, rng):
        param_vals = _cast_params(param_vals, dtype)
        with autograd.fresh_tape(), autograd.no_grad(), \
                bind_tensors(params, param_vals):
            b = ids.shape[0]
            total = s0 + max_new
            flat_b = b * nb
            # prefill ONCE on [b, s0] (all beams share the prompt), then
            # tile caches/logits across beams
            caches = core.init_cache(b, total)
            cache_vals = [(k._value, v._value) for k, v in caches]
            logits, cache_vals = _fwd(model, ids, cache_vals, 0)
            cache_vals = [(jnp.repeat(k, nb, axis=0),
                           jnp.repeat(v, nb, axis=0))
                          for k, v in cache_vals]
            last = jnp.repeat(logits[:, -1], nb, axis=0)   # [b*nb, V]
            V = last.shape[-1]

            ids_exp = jnp.repeat(ids, nb, axis=0)          # [b*nb, s0]
            out = jnp.concatenate(
                [ids_exp,
                 jnp.full((flat_b, max_new), pad_token_id, ids.dtype)], 1)
            # only beam 0 is live initially, or every beam proposes the same
            # tokens and top-k picks duplicates
            scores = jnp.tile(
                jnp.asarray([0.0] + [_NEG_INF] * (nb - 1), jnp.float32),
                (b, 1))                                   # [b, nb]
            done = jnp.zeros((b, nb), jnp.bool_)
            lengths = jnp.zeros((b, nb), jnp.int32)

            # continuation row for finished beams: pad has logp 0, the rest
            # -inf, so a done beam survives top-k with unchanged score
            done_row = jnp.full((V,), _NEG_INF
                                ).at[pad_token_id].set(0.0)

            def cond(c):
                cur, done = c[1], c[3]
                return jnp.logical_and(cur < total,
                                       jnp.logical_not(jnp.all(done)))

            def body(c):
                out, cur, scores, done, lengths, last, cache_vals = c
                lg = last.astype(jnp.float32)
                if temperature != 1.0:
                    lg = lg / temperature
                logp = jax.nn.log_softmax(lg, axis=-1).reshape(b, nb, V)
                logp = jnp.where(done[..., None], done_row[None, None, :],
                                 logp)
                cand = (scores[..., None] + logp).reshape(b, nb * V)
                top_scores, top_idx = jax.lax.top_k(cand, nb)   # [b, nb]
                beam_idx = (top_idx // V).astype(jnp.int32)
                tok = (top_idx % V).astype(jnp.int32)

                brow = jnp.arange(b, dtype=jnp.int32)[:, None]
                out = out.reshape(b, nb, total)[brow, beam_idx]
                out = out.reshape(flat_b, total)
                out = jax.lax.dynamic_update_slice(
                    out, tok.reshape(flat_b, 1).astype(out.dtype), (0, cur))
                prev_done = done[brow, beam_idx]
                lengths = jnp.where(prev_done, lengths[brow, beam_idx],
                                    lengths[brow, beam_idx] + 1)
                done = jnp.logical_or(prev_done, tok == eos)
                scores = top_scores

                def reorder(a):
                    sh = a.shape
                    return a.reshape((b, nb) + sh[1:])[brow, beam_idx] \
                            .reshape(sh)
                cache_vals = [(reorder(k), reorder(v))
                              for k, v in cache_vals]
                logits, cache_vals = _fwd(model, tok.reshape(flat_b, 1),
                                          cache_vals, cur)
                return (out, cur + 1, scores, done, lengths, logits[:, -1],
                        cache_vals)

            init = (out, jnp.asarray(s0, jnp.int32), scores, done, lengths,
                    last, cache_vals)
            out, _, scores, done, lengths, _, _ = jax.lax.while_loop(
                cond, body, init)

            final = penalize(scores, lengths)           # [b, nb]
            best = jnp.argmax(final, axis=-1)           # [b]
            brow = jnp.arange(b, dtype=jnp.int32)
            best_ids = out.reshape(b, nb, total)[brow, best]
            best_scores = final[brow, best]
            return best_ids, best_scores

    return jax.jit(gen)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def run_generate(model, input_ids, max_new_tokens=32,
                 decode_strategy="greedy", top_k=0, top_p=1.0,
                 temperature=1.0, num_beams=1, length_penalty=0.0,
                 eos_token_id=None, pad_token_id=0, seed=None,
                 dtype="bfloat16"):
    """dtype: compute dtype for decode. Incremental decode is pure
    weight-bandwidth (every step re-reads all parameters for a handful
    of tokens), so bf16 weights double tokens/sec on TPU — measured
    5.4k -> 10.7k tok/s on the 125M bench with bit-identical greedy
    tokens. Pass dtype=None to decode in the parameters' own dtype."""
    if decode_strategy not in ("greedy", "sampling", "beam_search"):
        raise ValueError(f"unknown decode_strategy {decode_strategy!r}")
    ids = input_ids._value if isinstance(input_ids, Tensor) \
        else jnp.asarray(np.asarray(input_ids), jnp.int32)
    if ids.ndim != 2:
        raise ValueError("input_ids must be [batch, prompt_len]")
    b, s0 = ids.shape

    # bind buffers as well as parameters: WeightOnlyInt8Linear/Embedding
    # carry wq/w_scale as persistable BUFFERS, and leaving them out of the
    # bound list bakes them into every cached trace as constants (one full
    # pinned copy of the quantized weights per (batch, prompt_len, ...)
    # cache key) and hides w_scale from _cast_params' decode-dtype cast
    named = list(model.named_parameters()) + [
        (n, b) for n, b in model.named_buffers() if b is not None]
    params = [p for _, p in named]
    # the parameter TREE is part of the cache identity: a structural
    # change (e.g. quant.quantize_weights_int8 swapping Linears) after
    # a cached trace would rebind the new flat param list against the
    # old trace's order and scramble weights silently. The sig tuple
    # itself is the key component (a hash could collide -> scramble).
    tree_sig = tuple((n, tuple(p.shape), str(p.dtype)) for n, p in named)
    from ..flags import get_flag
    key = (b, s0, int(max_new_tokens), decode_strategy, int(top_k),
           float(top_p), float(temperature), int(num_beams),
           float(length_penalty), eos_token_id, int(pad_token_id),
           str(dtype), bool(get_flag("use_pallas_decode_attention")),
           tree_sig)
    cache = model.__dict__.setdefault("_generate_cache", {})
    # evict traces built against a DIFFERENT tree: their closures pin
    # the replaced parameter set (e.g. the pre-quantize bf16 weights)
    # in device memory for the model's lifetime otherwise
    for k in [k for k in cache if k[-1] != tree_sig]:
        del cache[k]
    fn = cache.get(key)
    if fn is None:
        if decode_strategy == "beam_search":
            if num_beams < 2:
                raise ValueError("beam_search needs num_beams >= 2")
            fn = _build_beam_fn(model, params, s0, int(max_new_tokens),
                                num_beams, length_penalty, eos_token_id,
                                pad_token_id, temperature, dtype=dtype)
        else:
            select = _make_selector(decode_strategy, top_k, top_p,
                                    temperature)
            fn = _build_sample_fn(model, params, s0, int(max_new_tokens),
                                  select, eos_token_id, pad_token_id,
                                  dtype=dtype)
        cache[key] = fn

    if seed is not None:
        rng = jax.random.PRNGKey(seed)
    else:
        rng = default_generator().split()
    out, scores = fn([p._value for p in params], ids.astype(jnp.int32),
                     rng)
    return Tensor(out), Tensor(scores)


# ---------------------------------------------------------------------------
# cell-level decoding API (reference rnn.py parity)
# ---------------------------------------------------------------------------

class BeamSearchDecoder:
    """Reference `fluid/layers/rnn.py:866` analog for RNN-style cells.

    cell: callable (inputs [B, in], states pytree) -> (output [B, H],
    new_states); output is projected to vocab logits by `output_fn` (or is
    already logits). Used eagerly (inference post-processing path).
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _logits(self, cell_out):
        out = self.output_fn(cell_out) if self.output_fn else cell_out
        return out._value if isinstance(out, Tensor) else jnp.asarray(out)


def dynamic_decode(decoder, inits=None, max_step_num=64, batch_size=None,
                   **kwargs):
    """Greedy/beam decode driver for cell decoders
    (`fluid/layers/rnn.py:1583` analog). Returns (ids Tensor [b, <=max],
    scores Tensor [b]). Eager implementation: the per-step cell is ordinary
    eager code; fine for OCR-size decoding."""
    nb = decoder.beam_size
    end = decoder.end_token

    def tree_map(f, t):
        return jax.tree_util.tree_map(
            f, t, is_leaf=lambda x: isinstance(x, Tensor))

    def unwrap(t):
        return tree_map(lambda x: x._value if isinstance(x, Tensor) else x,
                        t)

    states = unwrap(inits)
    leaves = jax.tree_util.tree_leaves(states)
    if batch_size is None:
        if not leaves:
            raise ValueError("pass batch_size when inits has no tensors")
        batch_size = int(leaves[0].shape[0])
    b = batch_size

    # expand state to beams: [b, ...] -> [b*nb, ...]
    states = jax.tree_util.tree_map(
        lambda x: jnp.repeat(jnp.asarray(x), nb, axis=0), states)
    tok = jnp.full((b * nb,), decoder.start_token, jnp.int32)
    scores = jnp.tile(jnp.asarray([0.0] + [_NEG_INF] * (nb - 1)), (b, 1))
    done = np.zeros((b, nb), bool)
    seqs = [[[] for _ in range(nb)] for _ in range(b)]

    with autograd.no_grad():
        for _ in range(max_step_num):
            inp = Tensor(tok)
            if decoder.embedding_fn is not None:
                inp = decoder.embedding_fn(inp)
            cell_out, states = decoder.cell(
                inp, tree_map(lambda x: Tensor(x), states))
            logits = decoder._logits(cell_out)
            V = logits.shape[-1]
            logp = np.array(jax.nn.log_softmax(
                logits.astype(jnp.float32), axis=-1)).reshape(b, nb, V)
            done_row = np.full((V,), _NEG_INF)
            done_row[end] = 0.0
            logp[done] = done_row
            cand = (np.asarray(scores)[..., None] + logp).reshape(b, nb * V)
            top_idx = np.argsort(-cand, axis=-1)[:, :nb]
            scores = np.take_along_axis(cand, top_idx, axis=-1)
            beam_idx = top_idx // V
            toks = (top_idx % V).astype(np.int32)

            new_seqs, new_done = [], np.zeros_like(done)
            for i in range(b):
                row = []
                for j in range(nb):
                    src = seqs[i][beam_idx[i][j]]
                    was_done = done[i][beam_idx[i][j]]
                    t = int(toks[i][j])
                    row.append(list(src) if was_done else list(src) + [t])
                    new_done[i][j] = was_done or t == end
                new_seqs.append(row)
            seqs, done = new_seqs, new_done

            flat_beam = (np.arange(b)[:, None] * nb + beam_idx).reshape(-1)
            states = jax.tree_util.tree_map(
                lambda x: jnp.asarray(np.asarray(x)[flat_beam]),
                unwrap(states))
            tok = jnp.asarray(toks.reshape(-1))
            if done.all():
                break

    best = np.argmax(np.asarray(scores), axis=-1)
    out_seqs = [seqs[i][best[i]] for i in range(b)]
    max_len = max(1, max(len(s) for s in out_seqs))
    ids = np.full((b, max_len), end, np.int32)
    for i, s in enumerate(out_seqs):
        ids[i, :len(s)] = s
    return (Tensor(jnp.asarray(ids)),
            Tensor(jnp.asarray(np.asarray(scores)[np.arange(b), best],
                               np.float32)))
