"""paddle_tpu — a TPU-native deep learning framework.

Brand-new framework with the capabilities of the reference PaddlePaddle fork
(`/root/reference`), redesigned TPU-first: a single eager API whose autograd
tape records `jax.vjp` closures, so the same code runs eagerly (dygraph
analog) or traces under `paddle_tpu.jit.to_static` into one fused XLA program
(static-graph analog). Distribution is GSPMD sharding over a
`jax.sharding.Mesh` instead of NCCL program rewriting.
"""
__version__ = "0.1.0"

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.5 ships shard_map only under jax.experimental and without
    # the `axis_names` kwarg (manual-axis subset). This codebase targets
    # the stable `jax.shard_map` surface; adapt the old API in place:
    # axis_names=M maps to auto = mesh.axis_names - M, and check_rep is
    # forced off (partial-manual regions reject it on 0.4.x).
    def _shard_map_compat(f, mesh=None, in_specs=None, out_specs=None,
                          axis_names=None, check_rep=False, **kwargs):
        from jax.experimental.shard_map import shard_map as _sm
        full = _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False, **kwargs)
        auto = frozenset(mesh.axis_names) - frozenset(axis_names) \
            if axis_names is not None else frozenset()
        if not auto:
            return full

        part = _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False, auto=auto, **kwargs)

        def call(*args):
            if not _shard_map_compat._partial_auto_broken:
                try:
                    under_trace = not _jax.core.trace_state_clean()
                except Exception:
                    under_trace = False
                if under_trace:
                    # under an outer jit trace a partial-auto failure
                    # only surfaces at the OUTER compile, far from this
                    # try/except — go straight to fully-manual there
                    return full(*args)
                try:
                    return part(*args)
                except NotImplementedError:
                    # 0.4.x partial-auto is unimplemented for many
                    # prims; fully-manual is equivalent whenever the
                    # auto axes are unused inside the region (specs
                    # never mention them). Memoized process-wide: the
                    # failed attempt costs a full trace, so pay it once.
                    _shard_map_compat._partial_auto_broken = True
            return full(*args)
        return call

    _shard_map_compat._partial_auto_broken = False
    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "pcast"):
    # old jax has no varying/invariant replication tracking (we run its
    # shard_map with check_rep=False, where everything is varying), so
    # the new API's explicit pcast is semantically an identity here
    _jax.lax.pcast = lambda x, *args, **kwargs: x

from .core.dtype import (  # noqa: F401
    bool, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, set_default_dtype, get_default_dtype,
)
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.autograd import no_grad, enable_grad, set_grad_enabled, grad  # noqa: F401
from .core.random import seed, get_rng_state_tracker  # noqa: F401

from .tensor import *  # noqa: F401,F403
from .tensor import add_n  # noqa: F401

from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import amp  # noqa: F401
from . import metric  # noqa: F401
from . import framework  # noqa: F401
from . import device  # noqa: F401
from . import autograd  # noqa: F401
from . import utils  # noqa: F401
from . import enforce  # noqa: F401
from . import monitor  # noqa: F401
from . import cost_model  # noqa: F401
from . import telemetry  # noqa: F401
from . import resilience  # noqa: F401

from .framework import CPUPlace, TPUPlace, CUDAPlace, get_flags, set_flags  # noqa: F401
from .device import set_device, get_device, is_compiled_with_cuda  # noqa: F401
from .io.serialization import save, load  # noqa: F401

# heavier subpackages are imported lazily to keep import cost low
_LAZY = ("distributed", "vision", "text", "hapi", "profiler", "inference",
         "ops", "incubate", "static", "onnx", "fleet")


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "Model":
        from .hapi.model import Model
        return Model
    if name == "DataParallel":
        from .distributed.parallel import DataParallel
        return DataParallel
    if name == "summary":
        from .hapi.summary import summary
        return summary
    if name == "flops":
        from .hapi.flops import flops
        return flops
    if name == "flops_compiled":
        from .hapi.flops import flops_compiled
        return flops_compiled
    if name == "callbacks":
        from .hapi import callbacks
        return callbacks
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def disable_static(place=None):
    """No-op: paddle_tpu is always 'dygraph' (eager-traceable)."""


def enable_static():
    import warnings
    warnings.warn("paddle_tpu has no separate static mode; use "
                  "paddle_tpu.jit.to_static to compile", stacklevel=2)


def in_dynamic_mode():
    return True


def is_grad_enabled():
    from .core import autograd
    return autograd.grad_enabled()


# ---------------------------------------------------------------------------
# top-level API-parity shims (reference python/paddle/__init__.py surface)
# ---------------------------------------------------------------------------
from .nn import ParamAttr  # noqa: F401,E402
from . import fft  # noqa: F401,E402

VarBase = Tensor                       # 1.x alias
full_version = __version__
commit = "paddle-tpu-native"


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone parameter factory (reference
    `fluid/layers/tensor.py create_parameter`)."""
    from .nn.layer.layers import Layer
    if attr is None and name is not None:
        attr = ParamAttr(name=name)
    helper = Layer()
    return helper.create_parameter(list(shape), attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def batch(reader, batch_size, drop_last=False):
    """paddle.batch reader decorator (reference `fluid/../batch.py`)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def rank(input):  # noqa: A002
    """Number of dimensions, as a 0-d int Tensor (fluid.layers.rank)."""
    import numpy as _np
    n = input.ndim if hasattr(input, "ndim") else _np.asarray(input).ndim
    return Tensor(_np.asarray(n))


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor repr prints via numpy, so numpy's printoptions state is
    the single source of truth — just forward."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def enable_dygraph(place=None):
    """No-op: always eager."""


def disable_dygraph():
    import warnings
    warnings.warn("paddle_tpu has no static mode; use jit.to_static",
                  stacklevel=2)


def in_dygraph_mode():
    return True


def disable_signal_handler():
    """No-op (the reference unhooks its C++ fault handlers)."""


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_rocm():
    return False


def get_cuda_rng_state():
    """CUDA-API-parity shim: returns the framework RNG state."""
    from .core.random import default_generator
    return [default_generator().get_state()]


def set_cuda_rng_state(state_list):
    from .core.random import default_generator
    if state_list:
        default_generator().set_state(state_list[0])


# Place shims for API parity — framework.py owns the canonical aliases
from .framework import CUDAPinnedPlace, XPUPlace, NPUPlace  # noqa: F401,E402


def get_cudnn_version():
    return None                         # no cudnn in an XLA/TPU build


def check_shape(shape, op_name="check_shape",
                expected_shape_type=(list, tuple),
                expected_element_type=(int,),
                expected_tensor_dtype=("int32", "int64")):
    """Reference creation-op shape validation
    (`fluid/data_feeder.py:142`). A Tensor-valued shape is accepted when
    its dtype is in expected_tensor_dtype (the dynamic-shape program
    case); `all` must be the builtin — the tensor reduction op shadows
    it in this namespace."""
    import builtins
    import numpy as _np
    from .enforce import enforce
    from .core.tensor import Tensor
    if isinstance(shape, Tensor):
        enforce(str(shape.dtype).rsplit(".", 1)[-1] in expected_tensor_dtype,
                f"Tensor shape dtype must be one of "
                f"{expected_tensor_dtype}, got {shape.dtype}", op=op_name)
        return shape
    enforce(isinstance(shape, tuple(t for t in expected_shape_type
                                    if isinstance(t, type))),
            f"shape must be {expected_shape_type}, got {type(shape)}",
            op=op_name)
    shape = list(shape)
    ok = builtins.all(
        isinstance(s, tuple(expected_element_type) + (_np.integer,))
        and not isinstance(s, builtins.bool) for s in shape)
    enforce(ok, f"shape must be ints, got {shape}", op=op_name)
    return shape


def monkey_patch_math_varbase():
    """No-op: Tensor methods are registered at import time."""


def monkey_patch_variable():
    """No-op: there is no static Variable to patch."""


from .core import dtype  # noqa: F401,E402


from . import hub  # noqa: F401  (local-source hub + md5 weight loading)
from . import distribution  # noqa: F401
from . import sysconfig  # noqa: F401
from . import reader  # noqa: F401
from . import compat  # noqa: F401
from . import regularizer  # noqa: F401
from . import fluid  # noqa: F401  (legacy namespace shim)
