"""paddle_tpu — a TPU-native deep learning framework.

Brand-new framework with the capabilities of the reference PaddlePaddle fork
(`/root/reference`), redesigned TPU-first: a single eager API whose autograd
tape records `jax.vjp` closures, so the same code runs eagerly (dygraph
analog) or traces under `paddle_tpu.jit.to_static` into one fused XLA program
(static-graph analog). Distribution is GSPMD sharding over a
`jax.sharding.Mesh` instead of NCCL program rewriting.
"""
__version__ = "0.1.0"

from .core.dtype import (  # noqa: F401
    bool, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, set_default_dtype, get_default_dtype,
)
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.autograd import no_grad, enable_grad, set_grad_enabled, grad  # noqa: F401
from .core.random import seed, get_rng_state_tracker  # noqa: F401

from .tensor import *  # noqa: F401,F403
from .tensor import add_n  # noqa: F401

from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import amp  # noqa: F401
from . import metric  # noqa: F401
from . import framework  # noqa: F401
from . import device  # noqa: F401
from . import autograd  # noqa: F401
from . import utils  # noqa: F401
from . import enforce  # noqa: F401
from . import monitor  # noqa: F401

from .framework import CPUPlace, TPUPlace, CUDAPlace, get_flags, set_flags  # noqa: F401
from .device import set_device, get_device, is_compiled_with_cuda  # noqa: F401
from .io.serialization import save, load  # noqa: F401

# heavier subpackages are imported lazily to keep import cost low
_LAZY = ("distributed", "vision", "text", "hapi", "profiler", "inference",
         "ops", "incubate", "static", "onnx")


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "Model":
        from .hapi.model import Model
        return Model
    if name == "DataParallel":
        from .distributed.parallel import DataParallel
        return DataParallel
    if name == "summary":
        from .hapi.summary import summary
        return summary
    if name == "flops":
        from .hapi.flops import flops
        return flops
    if name == "flops_compiled":
        from .hapi.flops import flops_compiled
        return flops_compiled
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def disable_static(place=None):
    """No-op: paddle_tpu is always 'dygraph' (eager-traceable)."""


def enable_static():
    import warnings
    warnings.warn("paddle_tpu has no separate static mode; use "
                  "paddle_tpu.jit.to_static to compile", stacklevel=2)


def in_dynamic_mode():
    return True


def is_grad_enabled():
    from .core import autograd
    return autograd.grad_enabled()
