"""paddle_tpu.nn.functional — mirrors `python/paddle/nn/functional/`."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose,
)
from .norm import (  # noqa: F401
    layer_norm, batch_norm, instance_norm, group_norm, local_response_norm,
    fused_add_layer_norm,
)
from .pooling import (  # noqa: F401
    max_pool1d, max_pool2d, max_pool3d, avg_pool1d, avg_pool2d, avg_pool3d,
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
    max_unpool2d,
)
from ...tensor.manipulation import diag_embed  # noqa: F401
from .loss import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy, nll_loss, mse_loss, l1_loss,
    smooth_l1_loss, binary_cross_entropy, binary_cross_entropy_with_logits,
    kl_div, margin_ranking_loss, hinge_embedding_loss, cosine_embedding_loss,
    triplet_margin_loss, square_error_cost, log_loss, sigmoid_focal_loss,
    dice_loss, hsigmoid_loss, margin_cross_entropy,
    ctc_loss, npair_loss,
)
from .vision import (  # noqa: F401
    pixel_shuffle, pixel_unshuffle, channel_shuffle, affine_grid, grid_sample,
    temporal_shift,
)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Reference `operators/sequence_ops/sequence_mask_op.cc` — mask[i, j] =
    j < x[i]."""
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    from ...core.dtype import convert_dtype
    from ...tensor._helpers import ensure_tensor
    x = ensure_tensor(x)
    v = x._value
    if maxlen is None:
        import numpy as np
        maxlen = int(np.asarray(v).max())
    elif isinstance(maxlen, Tensor):
        maxlen = int(maxlen.item())
    mask = jnp.arange(maxlen) < v[..., None]
    return Tensor(mask.astype(convert_dtype(dtype)))


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Fused attention entry point. Uses the Pallas flash-attention kernel on
    TPU when shapes allow (paddle_tpu.ops.flash_attention), else the XLA
    composed path. Layout: [batch, seqlen, num_heads, head_dim] (paddle
    convention)."""
    from ...ops.attention import scaled_dot_product_attention as sdpa
    return sdpa(query, key, value, attn_mask=attn_mask, dropout_p=dropout_p,
                is_causal=is_causal, training=training)
