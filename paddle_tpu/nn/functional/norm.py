"""Normalization functionals.

Parity: `python/paddle/nn/functional/norm.py` (reference kernels
`operators/batch_norm_op.cu`, `layer_norm_op.cu`, `group_norm_op.cu`,
`instance_norm_op.cu`). XLA fuses the reduce+scale+shift chains; layer_norm
is also provided as a Pallas kernel in `paddle_tpu.ops.pallas` for the
residual+dropout fusion cases.
"""
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...tensor._helpers import ensure_tensor


@jax.custom_vjp
def _scale_shift(x, w, b):
    """y = x * w + b applied in x's dtype (no f32 stream upcast), with a
    hand-written vjp whose PARAM-GRAD reductions accumulate in f32 — the
    automatic vjp of a bf16 multiply would sum the [B*S]-long bias/weight
    gradients in bf16 (~2 digits lost over 16k tokens)."""
    return x * w.astype(x.dtype) + b.astype(x.dtype)


def _scale_shift_fwd(x, w, b):
    return _scale_shift(x, w, b), (x, w)


def _scale_shift_bwd(res, g):
    x, w = res
    red = tuple(range(g.ndim - w.ndim))
    dx = g * w.astype(g.dtype)
    dw = jnp.sum(g * x, axis=red, dtype=jnp.float32).astype(w.dtype)
    db = jnp.sum(g, axis=red, dtype=jnp.float32).astype(w.dtype)
    return dx, dw, db


_scale_shift.defvjp(_scale_shift_fwd, _scale_shift_bwd)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    x = ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    naxes = tuple(range(-len(normalized_shape), 0))

    def fn(v, *wb):
        # statistics accumulate in the amp-list dtype for "layer_norm"
        # (f32 by default — black list; bf16 if the user white-lists it);
        # elementwise math stays in the input dtype so no f32 activation
        # copy is materialized (same bandwidth reasoning as batch_norm)
        from ...amp import amp_op_dtype
        acc = amp_op_dtype("layer_norm", jnp.float32)
        mean = jnp.mean(v, axis=naxes, keepdims=True, dtype=acc)
        d = v - mean.astype(v.dtype)
        var = jnp.mean(jnp.square(d), axis=naxes, keepdims=True,
                       dtype=acc)
        out = d * jax.lax.rsqrt(var + epsilon).astype(v.dtype)
        # scale/shift applied in the INPUT dtype: multiplying by the f32
        # params would upcast the whole [B,S,D] stream to f32 (measured
        # ~6.7GB/step of residual-stream traffic on the GPT bench);
        # _scale_shift's custom vjp keeps the param-grad reductions f32
        if weight is not None and bias is not None:
            return _scale_shift(out, wb[0], wb[1])
        i = 0
        if weight is not None:
            out = out * wb[i]        # f32 upcast: rare config, safe grads
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply(fn, *args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    """When training, returns output computed from batch stats AND updates
    running stats in place on the provided tensors (dygraph semantics,
    reference `operators/batch_norm_op.cc`). Under `to_static` the buffer
    update is captured by the functional-state machinery in paddle_tpu.jit."""
    x = ensure_tensor(x)
    running_mean = ensure_tensor(running_mean)
    running_var = ensure_tensor(running_var)
    ch_axis = 1 if data_format[1] == "C" else x.ndim - 1
    red_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = -1

    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        def _stats(v):
            # f32-ACCUMULATING reductions straight off the (possibly bf16)
            # input: `v.astype(f32)` first would materialize a full f32
            # activation copy in HLO (measured: +14 GB/step traffic on
            # ResNet-50/64 — conv nets are bandwidth-bound on TPU). The
            # variance pass squares the CENTERED bf16 values, avoiding the
            # E[x^2]-E[x]^2 cancellation while keeping elementwise work in
            # the input dtype.
            mean = jnp.mean(v, axis=red_axes, dtype=jnp.float32)
            d = v - mean.astype(v.dtype).reshape(bshape)
            var = jnp.mean(jnp.square(d), axis=red_axes, dtype=jnp.float32)
            return mean, var

        # update running stats in place with (stop-gradient) batch stats;
        # tracer-safe under jit via the functional-state capture in paddle_tpu.jit
        bmean, bvar = _stats(x._value)
        running_mean._value = (momentum * running_mean._value.astype(jnp.float32)
                               + (1 - momentum) * bmean).astype(running_mean._value.dtype)
        running_var._value = (momentum * running_var._value.astype(jnp.float32)
                              + (1 - momentum) * bvar).astype(running_var._value.dtype)

        def fn(v, *wb):
            # batch stats recomputed inside so grads flow through mean/var.
            # The normalize is FOLDED into one per-channel multiply-add in
            # the INPUT dtype: out = v*a + c with a = w*rsqrt(var+eps),
            # c = b - mean*a — so every activation-sized tensor (and the
            # vjp's saved residuals) stays bf16 under AMP.
            mean, var = _stats(v)
            a = jax.lax.rsqrt(var + epsilon)
            i = 0
            if weight is not None:
                a = a * wb[i]
                i += 1
            c = -mean * a
            if bias is not None:
                c = c + wb[i]
            return v * a.reshape(bshape).astype(v.dtype) + \
                c.reshape(bshape).astype(v.dtype)
    else:
        mean_c, var_c = running_mean._value, running_var._value

        def fn(v, *wb):
            a = jax.lax.rsqrt(var_c.astype(jnp.float32) + epsilon)
            i = 0
            if weight is not None:
                a = a * wb[i]
                i += 1
            c = -mean_c.astype(jnp.float32) * a
            if bias is not None:
                c = c + wb[i]
            return v * a.reshape(bshape).astype(v.dtype) + \
                c.reshape(bshape).astype(v.dtype)

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply(fn, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    x = ensure_tensor(x)
    ch_axis = 1 if data_format[1] == "C" else x.ndim - 1
    red_axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else \
        tuple(i for i in range(1, x.ndim - 1))
    bshape = [1] * x.ndim
    bshape[ch_axis] = -1

    def fn(v, *wb):
        mean = jnp.mean(v, axis=red_axes, keepdims=True)
        var = jnp.var(v, axis=red_axes, keepdims=True)
        out = (v - mean) * jnp.power(var + eps, -0.5)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply(fn, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C"

    def fn(v, *wb):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        n, c = v.shape[0], v.shape[1]
        spatial = v.shape[2:]
        g = v.reshape(n, num_groups, c // num_groups, *spatial)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jnp.power(var + epsilon, -0.5)).reshape(v.shape)
        bshape = [1, c] + [1] * len(spatial)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply(fn, *args)


def fused_add_layer_norm(x, residual, weight, bias, epsilon=1e-05,
                         name=None):
    """(LayerNorm(x + residual), x + residual) — the pre-LN transformer
    residual site in one op. Dispatches to the Pallas pair kernel
    (`ops/pallas_layernorm.py`, measured 1.69x the composed XLA lowering
    on v5e at GPT bench shapes) when `use_pallas_layernorm` is on and
    shapes divide; composed XLA with identical f32-moment numerics
    otherwise. Reference analog: the fused_bias_dropout_residual_
    layer_norm op family / skip_layernorm_fuse_pass.cc."""
    x = ensure_tensor(x)
    residual = ensure_tensor(residual)
    weight = ensure_tensor(weight)
    bias = ensure_tensor(bias)

    def fn(v, r, w, b):
        from ...flags import get_flag
        from ...ops.pallas_layernorm import (fused_add_layer_norm_pair,
                                             _BLOCK_ROWS)
        lead = v.shape[:-1]
        d = v.shape[-1]
        rows = 1
        for n in lead:
            rows *= int(n)
        if (get_flag("use_pallas_layernorm") and rows % _BLOCK_ROWS == 0
                and d % 128 == 0 and jax.default_backend() == "tpu"):
            out2, carry2 = fused_add_layer_norm_pair(
                v.reshape(-1, d), r.reshape(-1, d), w, b, epsilon)
            return out2.reshape(*lead, d), carry2.reshape(*lead, d)
        # composed path: same bandwidth discipline as layer_norm above —
        # f32 moments, elementwise math and scale/shift in input dtype
        # (no f32 copy of the [.., d] stream is materialized)
        h = v + r
        from ...amp import amp_op_dtype
        acc = amp_op_dtype("layer_norm", jnp.float32)
        mean = jnp.mean(h, axis=-1, keepdims=True, dtype=acc)
        dlt = h - mean.astype(h.dtype)
        var = jnp.mean(jnp.square(dlt), axis=-1, keepdims=True, dtype=acc)
        out = dlt * jax.lax.rsqrt(var + epsilon).astype(h.dtype)
        return _scale_shift(out, w, b), h

    return apply(fn, x, residual, weight, bias)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def fn(v):
        sq = jnp.square(v)
        ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
        c = v.shape[ch_axis]
        half = size // 2
        pads = [(0, 0)] * v.ndim
        pads[ch_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        acc = jnp.zeros_like(v)
        for i in range(size):
            sl = [slice(None)] * v.ndim
            sl[ch_axis] = slice(i, i + c)
            acc = acc + padded[tuple(sl)]
        return v / jnp.power(k + alpha * acc, beta)
    return apply(fn, x)
