"""Vision functionals: pixel_shuffle, grid_sample, affine_grid.

Parity: `python/paddle/nn/functional/vision.py` (reference
`operators/pixel_shuffle_op.cc`, `grid_sampler_op.cu`, `affine_grid_op.cc`).
"""
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...tensor._helpers import ensure_tensor


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = int(upscale_factor)

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = jnp.transpose(v, (0, 1, 4, 2, 5, 3))
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = jnp.transpose(v, (0, 1, 3, 2, 4, 5))
        return v.reshape(n, h * r, w * r, c // (r * r))
    return apply(fn, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = int(downscale_factor)

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = jnp.transpose(v, (0, 1, 3, 5, 2, 4))
            return v.reshape(n, c * r * r, h // r, w // r)
        raise NotImplementedError
    return apply(fn, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def fn(v):
        n, c, h, w = v.shape
        v = v.reshape(n, groups, c // groups, h, w)
        v = jnp.swapaxes(v, 1, 2)
        return v.reshape(n, c, h, w)
    return apply(fn, x)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    theta = ensure_tensor(theta)
    if isinstance(out_shape, Tensor):
        out_shape = [int(s) for s in out_shape.numpy()]
    n, c, h, w = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # h,w,3
        out = jnp.einsum("hwk,njk->nhwj", base, th)
        return out
    return apply(fn, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    x, grid = ensure_tensor(x), ensure_tensor(grid)

    def fn(v, g):
        n, c, h, w = v.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(ix, iy):
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            val = v[jnp.arange(n)[:, None, None], :, iyc, ixc]  # n,gh,gw,c
            if padding_mode == "zeros":
                ok = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) &
                      (iy <= h - 1)).astype(v.dtype)[..., None]
                val = val * ok
            return val

        if mode == "nearest":
            out = sample(jnp.round(fx).astype(jnp.int32),
                         jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wa = ((x1 - fx) * (y1 - fy))[..., None]
            wb = ((x1 - fx) * (fy - y0))[..., None]
            wc = ((fx - x0) * (y1 - fy))[..., None]
            wd = ((fx - x0) * (fy - y0))[..., None]
            out = (sample(x0, y0) * wa + sample(x0, y1) * wb +
                   sample(x1, y0) * wc + sample(x1, y1) * wd)
        return jnp.transpose(out, (0, 3, 1, 2))  # back to NCHW
    return apply(fn, x, grid)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    # param ORDER follows the reference (`fluid/layers/nn.py`
    # temporal_shift: name before data_format) for positional users
    x = ensure_tensor(x)

    def fn(v):
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])],
                               axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                                 v[:, :-1, fold:2 * fold]], axis=1)
        rest = v[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2)
        return out.reshape(nt, c, h, w)
    return apply(fn, x)
