"""Common functionals: linear, dropout, pad, embedding, interpolate, ...

Parity: `python/paddle/nn/functional/common.py` + `input.py` (reference
kernels `operators/matmul_v2_op.cc` + bias fusion, `dropout_op.cu`,
`pad3d_op.cc`, `lookup_table_v2_op.cu`, `interpolate_v2_op.cc`).
`linear` is the MXU workhorse: XLA fuses matmul+bias+activation.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...core.random import next_key
from ...tensor._helpers import ensure_tensor


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W shaped [in, out] (paddle convention). Under
    amp.auto_cast the operands are cast to the compute dtype so the matmul
    hits the MXU at bf16 rate (the white-list cast the reference's tracer
    inserts, `imperative/amp_auto_cast.cc`)."""
    from ...amp import maybe_cast_to_compute as _amp
    from ...enforce import enforce
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    enforce(x.shape[-1] == weight.shape[0],
            f"x last dim {x.shape[-1]} != weight rows {weight.shape[0]} "
            f"(x {list(x.shape)}, weight {list(weight.shape)})",
            op="linear",
            hint="paddle stores Linear weight as [in_features, "
                 "out_features]; transpose torch-layout weights")
    if bias is None:
        return apply(lambda v, w: jnp.matmul(_amp(v, "linear"), _amp(w, "linear")), x, weight)
    bias = ensure_tensor(bias)
    return apply(lambda v, w, b: jnp.matmul(_amp(v, "linear"), _amp(w, "linear")) +
                 _amp(b, "linear"), x, weight, bias)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda v: v * (1.0 - p), x)
        return x
    if p == 1.0:
        return apply(lambda v: jnp.zeros_like(v), x)
    key = next_key()
    ax = axis if axis is None else (
        [axis] if isinstance(axis, int) else list(axis))

    def fn(v):
        if ax is None:
            mshape = v.shape
        else:
            mshape = tuple(v.shape[i] if i in ax else 1 for i in range(v.ndim))
        keep = jax.random.bernoulli(key, 1.0 - p, mshape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), jnp.zeros((), v.dtype))
        return jnp.where(keep, v, jnp.zeros((), v.dtype))
    return apply(fn, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x
    key = next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
    b = -a * alpha_p * p

    def fn(v):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        return a * jnp.where(keep, v, alpha_p) + b
    return apply(fn, x)


_PAD_MODES = {"constant": "constant", "reflect": "reflect",
              "replicate": "edge", "circular": "wrap"}


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()  # noqa: A001
    pad = [int(p) for p in pad]  # noqa: A001
    nd = x.ndim
    if len(pad) == 2 * nd:
        # full-rank paddle format: [before0, after0, before1, after1, ...]?
        # paddle uses per-dim pairs in *reverse* only for partial specs; the
        # full form is ordered by dim.
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial spec applies to trailing spatial dims (torch/paddle style:
        # last dim first)
        widths = [(0, 0)] * nd
        spatial = len(pad) // 2
        if "C" in data_format and data_format.index("C") == 1:
            dims = list(range(2, 2 + spatial))
        else:
            dims = list(range(1, 1 + spatial))
        # paddle pad spec: [left, right, top, bottom, front, back] maps from
        # innermost spatial dim outward
        for i, d in enumerate(reversed(dims)):
            widths[d] = (pad[2 * i], pad[2 * i + 1])
    jmode = _PAD_MODES.get(mode, mode)

    def fn(v):
        if jmode == "constant":
            return jnp.pad(v, widths, mode="constant", constant_values=value)
        return jnp.pad(v, widths, mode=jmode)
    return apply(fn, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Lookup rows of weight. On TPU this is an XLA gather; grads produce
    dense scatter-adds (the reference used SelectedRows sparse grads,
    `operators/lookup_table_v2_op.cu` — XLA handles the scatter)."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    idx = x._value.astype(jnp.int32)

    def fn(w):
        out = jnp.take(w, jnp.clip(idx, 0, w.shape[0] - 1), axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out
    return apply(fn, weight)


def one_hot(x, num_classes, name=None):
    from ...core.dtype import get_default_dtype
    x = ensure_tensor(x)
    return Tensor(jax.nn.one_hot(x._value, int(num_classes),
                                 dtype=get_default_dtype()))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = ensure_tensor(label)

    def fn(v):
        k = v.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._value if isinstance(prior_dist, Tensor) else jnp.asarray(prior_dist)
            return (1 - epsilon) * v + epsilon * pd
        return (1 - epsilon) * v + epsilon / k
    return apply(fn, label)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = ensure_tensor(x)

    def fn(v):
        if p == 2:
            n = jnp.sqrt(jnp.sum(jnp.square(v), axis=axis, keepdims=True))
        else:
            n = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)
    return apply(fn, x)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    x1, x2 = ensure_tensor(x1), ensure_tensor(x2)

    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.sqrt(jnp.sum(a * a, axis=axis) * jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(den, eps)
    return apply(fn, x1, x2)


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, weight = ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)

    def fn(a, b, w):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        return out
    out = apply(fn, x1, x2, weight)
    if bias is not None:
        bias = ensure_tensor(bias)
        out = apply(lambda o, c: o + c, out, bias)
    return out


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    channel_last = data_format[-1] == "C"
    spatial_dims = list(range(1, nd - 1)) if channel_last else list(range(2, nd))
    in_sizes = [x._value.shape[d] for d in spatial_dims]
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in np.asarray(size._value)]
        out_sizes = [int(s.item()) if isinstance(s, Tensor) else int(s)
                     for s in (size if isinstance(size, (list, tuple)) else [size])]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * len(in_sizes)
        out_sizes = [int(s * float(f)) for s, f in zip(in_sizes, sf)]
    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def fn(v):
        shape = list(v.shape)
        for d, s in zip(spatial_dims, out_sizes):
            shape[d] = s
        if mode == "nearest":
            # exact nearest via index gather (jax.image nearest matches)
            return jax.image.resize(v, shape, method="nearest")
        if align_corners:
            # build index grids per spatial dim and gather-interp
            return _resize_align_corners(v, spatial_dims, out_sizes, jmode)
        return jax.image.resize(v, shape, method=jmode)
    return apply(fn, x)


def _resize_align_corners(v, spatial_dims, out_sizes, method):
    out = v
    for d, s in zip(spatial_dims, out_sizes):
        n = out.shape[d]
        if s == 1 or n == 1:
            idx = jnp.zeros((s,), dtype=jnp.float32)
        else:
            idx = jnp.linspace(0.0, n - 1, s)
        lo = jnp.floor(idx).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, n - 1)
        w = (idx - lo).astype(out.dtype)
        shape = [1] * out.ndim
        shape[d] = s
        w = w.reshape(shape)
        take_lo = jnp.take(out, lo, axis=d)
        take_hi = jnp.take(out, hi, axis=d)
        if method == "nearest":
            out = jnp.where(w > 0.5, take_hi, take_lo)
        else:
            out = take_lo * (1 - w) + take_hi * w
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference `operators/math/im2col.cc`, unfold_op)."""
    x = ensure_tensor(x)
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]

    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])])
        oh = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = v[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                       j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)
    return apply(fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    x = ensure_tensor(x)
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def fn(v):
        n, ckk, L = v.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os_[0] + 2 * pd[0], os_[1] + 2 * pd[1]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        v = v.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), dtype=v.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                             j * dl[1]: j * dl[1] + ow * st[1]: st[1]].add(
                    v[:, :, i, j])
        return out[:, :, pd[0]: pd[0] + os_[0], pd[1]: pd[1] + os_[1]]
    return apply(fn, x)


def gather_tree(ids, parents):
    """Walk beam-search ancestry back from the last step so each beam
    holds its full token path (reference `operators/gather_tree_op.cc`).
    ids/parents: [max_time, batch, beam] -> gathered ids, same shape."""
    ids = ensure_tensor(ids)
    pv = ensure_tensor(parents)._value.astype(jnp.int32)

    def fn(iv):
        T, B, W = iv.shape
        bidx = jnp.arange(B)[:, None]

        def step(carry, t):
            beams = carry                         # [B, W] beam index at t+1
            tok = iv[t][bidx, beams]              # tokens along the path
            par = pv[t][bidx, beams]
            return par, tok

        _, toks = jax.lax.scan(step, jnp.broadcast_to(jnp.arange(W), (B, W)),
                               jnp.arange(T - 1, -1, -1))
        return jnp.flip(toks, axis=0)            # back to time order

    return apply(fn, ids)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample class centers for partial-FC training (reference
    `operators/class_center_sample_op.cu`): EVERY positive class is
    kept (paddle contract — the output grows past num_samples when the
    batch touches more classes than that), then deterministic negative
    classes fill the remainder. Host-side eager op (the output size is
    data-dependent, like the reference's); returns
    (remapped_label, sampled_class_index)."""
    lv = np.asarray(ensure_tensor(label).numpy()).astype(np.int64).ravel()
    pos = np.unique(lv)
    n_out = max(int(num_samples), len(pos))
    negatives = np.setdiff1d(np.arange(num_classes, dtype=np.int64), pos,
                             assume_unique=True)
    sampled = np.concatenate([pos, negatives[:n_out - len(pos)]])
    lookup = {int(c): i for i, c in enumerate(sampled)}
    remap = np.asarray([lookup[int(c)] for c in lv], np.int32)
    return Tensor(jnp.asarray(remap)), Tensor(jnp.asarray(
        sampled.astype(np.int32)))
