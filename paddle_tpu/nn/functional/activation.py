"""Activation functionals.

Parity: `python/paddle/nn/functional/activation.py` (reference kernels
`operators/activation_op.cc/.cu`). All fuse into adjacent matmuls via XLA on
TPU — no hand-written fusion needed (reference needed
`fused_elemwise_activation`).
"""
import jax
import jax.numpy as jnp
import jax.nn as jnn

from ...core.tensor import Tensor, apply
from ...tensor._helpers import ensure_tensor, unary


def _u(fn):
    def op(x, name=None):
        return unary(fn, ensure_tensor(x))
    return op


relu = _u(jnn.relu)
relu6 = _u(jnn.relu6)
sigmoid = _u(jnn.sigmoid)
tanh = _u(jnp.tanh)
silu = _u(jnn.silu)
swish = _u(jnn.silu)
mish = _u(lambda v: v * jnp.tanh(jnn.softplus(v)))
softsign = _u(jnn.soft_sign)
tanhshrink = _u(lambda v: v - jnp.tanh(v))
log_sigmoid = _u(jnn.log_sigmoid)


def gelu(x, approximate=False, name=None):
    return unary(lambda v: jnn.gelu(v, approximate=approximate),
                 ensure_tensor(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return unary(lambda v: jnn.leaky_relu(v, negative_slope), ensure_tensor(x))


def elu(x, alpha=1.0, name=None):
    return unary(lambda v: jnn.elu(v, alpha), ensure_tensor(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return unary(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)),
                 ensure_tensor(x))


def celu(x, alpha=1.0, name=None):
    return unary(lambda v: jnn.celu(v, alpha), ensure_tensor(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return unary(lambda v: jnp.clip(v, min, max), ensure_tensor(x))


def hardshrink(x, threshold=0.5, name=None):
    return unary(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0),
                 ensure_tensor(x))


def softshrink(x, threshold=0.5, name=None):
    return unary(lambda v: jnp.where(v > threshold, v - threshold,
                                     jnp.where(v < -threshold, v + threshold,
                                               0.0)), ensure_tensor(x))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return unary(lambda v: jnp.clip(slope * v + offset, 0.0, 1.0),
                 ensure_tensor(x))


def hardswish(x, name=None):
    return unary(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0,
                 ensure_tensor(x))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return unary(lambda v: jnp.where(beta * v > threshold, v,
                                     jnn.softplus(beta * v) / beta),
                 ensure_tensor(x))


def prelu(x, weight, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)

    def fn(v, w):
        if w.size > 1 and v.ndim > 1:
            ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
            shape = [1] * v.ndim
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(v > 0, v, w * v)
    return apply(fn, x, weight)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    x = ensure_tensor(x)
    if training:
        from ...core.random import next_key
        key = next_key()

        def fn(v):
            a = jax.random.uniform(key, v.shape, minval=lower, maxval=upper)
            return jnp.where(v >= 0, v, a.astype(v.dtype) * v)
        return apply(fn, x)
    mid = (lower + upper) / 2.0
    return unary(lambda v: jnp.where(v >= 0, v, mid * v), x)


def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)

    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return apply(fn, x)


def softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    return apply(lambda v: jnn.softmax(v, axis=int(axis)), x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    return apply(lambda v: jnn.log_softmax(v, axis=int(axis)), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    x = ensure_tensor(x)
    from ...core.random import next_key
    key = next_key()

    def fn(v):
        g = jax.random.gumbel(key, v.shape, dtype=v.dtype)
        y = jnn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y
    return apply(fn, x)


def glu(x, axis=-1, name=None):
    return unary(lambda v: jnn.glu(v, axis=axis), ensure_tensor(x))


def thresholded_relu(x, threshold=1.0, name=None):
    return unary(lambda v: jnp.where(v > threshold, v, 0.0), ensure_tensor(x))


# in-place functional variants (reference relu_/elu_/softmax_/tanh_):
# mutate the input tensor through the recorded in-place path and
# return it

def relu_(x, name=None):
    return x._inplace_apply(lambda v: jnp.maximum(v, 0))


def elu_(x, alpha=1.0, name=None):
    return x._inplace_apply(
        lambda v: jnp.where(v > 0, v, alpha * jnp.expm1(v)))


def tanh_(x, name=None):
    return x._inplace_apply(jnp.tanh)


def softmax_(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    dt = convert_dtype(dtype) if dtype is not None else None
    return x._inplace_apply(
        lambda v: jnn.softmax(v.astype(dt) if dt else v, axis=axis))
