"""Pooling functionals via `lax.reduce_window`.

Parity: `python/paddle/nn/functional/pooling.py` (reference
`operators/pool_op.cc`, cudnn pooling). reduce_window lowers to efficient
TPU vector ops.
"""
import numpy as np
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply
from ...tensor._helpers import ensure_tensor
from .conv import _norm_tuple, _norm_padding


def _pool(x, kernel, stride, padding, nd, channel_last, reducer, init,
          ceil_mode=False, count_include_pad=True, divisor_override=None,
          is_avg=False, exclusive=True):
    kernel = _norm_tuple(kernel, nd)
    stride = _norm_tuple(stride if stride is not None else kernel, nd)
    pad = _norm_padding(padding, nd)

    if channel_last:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride

    def fn(v):
        if isinstance(pad, str):
            pads = pad
        else:
            if channel_last:
                pads = [(0, 0)] + list(pad) + [(0, 0)]
            else:
                pads = [(0, 0), (0, 0)] + list(pad)
        if is_avg:
            # init must be a CONCRETE numpy scalar (never a jax array —
            # a traced init breaks reduce_window's monoid recognition and
            # reverse-mode linearization); np handles bf16 via ml_dtypes
            zero = np.zeros((), np.dtype(v.dtype))
            summed = lax.reduce_window(v, zero, lax.add, dims, strides, pads)
            if divisor_override:
                return summed / divisor_override
            if not exclusive or isinstance(pads, str):
                return summed / np.prod(kernel)
            counts = lax.reduce_window(jnp.ones_like(v), zero, lax.add, dims,
                                       strides, pads)
            return summed / counts
        neg_inf = np.asarray(-np.inf, np.dtype(v.dtype))[()]
        return lax.reduce_window(v, neg_inf, reducer, dims, strides, pads)
    return apply(fn, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    x = ensure_tensor(x)
    out = _pool(x, kernel_size, stride, padding, 1, False, lax.max, -jnp.inf)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    out = _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                lax.max, -jnp.inf)
    if return_mask:
        idx = _pool_indices(x, kernel_size, stride, padding, out)
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    x = ensure_tensor(x)
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 lax.max, -jnp.inf)


def _pool_indices(x, kernel_size, stride, padding, out):
    # flat indices of maxima (for unpool); computed via comparison gather
    xv, ov = x._value, out._value
    n, c, h, w = xv.shape
    k = _norm_tuple(kernel_size, 2)
    s = _norm_tuple(stride if stride is not None else kernel_size, 2)
    oh, ow = ov.shape[2], ov.shape[3]
    idx = jnp.zeros((n, c, oh, ow), dtype=jnp.int32)
    best = jnp.full((n, c, oh, ow), -jnp.inf, dtype=jnp.float32)
    for i in range(k[0]):
        for j in range(k[1]):
            sl = xv[:, :, i: i + oh * s[0]: s[0], j: j + ow * s[1]: s[1]]
            rows = jnp.arange(oh) * s[0] + i
            cols = jnp.arange(ow) * s[1] + j
            flat = rows[:, None] * w + cols[None, :]
            better = sl.astype(jnp.float32) > best
            best = jnp.where(better, sl.astype(jnp.float32), best)
            idx = jnp.where(better, flat[None, None], idx)
    return Tensor(idx)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    x = ensure_tensor(x)
    return _pool(x, kernel_size, stride, padding, 1, False, lax.add, 0.0,
                 is_avg=True, exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    x = ensure_tensor(x)
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 lax.add, 0.0, is_avg=True, exclusive=exclusive,
                 divisor_override=divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    x = ensure_tensor(x)
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 lax.add, 0.0, is_avg=True, exclusive=exclusive,
                 divisor_override=divisor_override)


def _adaptive_axes(in_size, out_size):
    # exact adaptive pooling: per output cell start/end like the reference
    starts = [(i * in_size) // out_size for i in range(out_size)]
    ends = [-(-((i + 1) * in_size) // out_size) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, nd, mode, channel_last=False):
    x = ensure_tensor(x)
    out_sizes = _norm_tuple(output_size, nd)
    spatial_off = 1 if channel_last else 2

    def fn(v):
        out = v
        for d in range(nd):
            axis = spatial_off + d
            in_size = out.shape[axis]
            osz = out_sizes[d] if out_sizes[d] is not None else in_size
            starts, ends = _adaptive_axes(in_size, osz)
            if all(e - s == ends[0] - starts[0] for s, e in zip(starts, ends)) \
                    and in_size % osz == 0:
                # uniform windows: reshape-reduce (fast path)
                k = in_size // osz
                shp = out.shape[:axis] + (osz, k) + out.shape[axis + 1:]
                r = out.reshape(shp)
                out = jnp.mean(r, axis=axis + 1) if mode == "avg" else \
                    jnp.max(r, axis=axis + 1)
            else:
                slices = []
                for s, e in zip(starts, ends):
                    sl = jnp.take(out, jnp.arange(s, e), axis=axis)
                    red = jnp.mean(sl, axis=axis, keepdims=True) \
                        if mode == "avg" else jnp.max(sl, axis=axis, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=axis)
        return out
    return apply(fn, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "max")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Inverse of max_pool2d(return_mask=True): scatter pooled values to
    the flat H*W positions recorded in `indices`
    (reference `operators/unpool_op.cc`)."""
    x = ensure_tensor(x)
    indices = ensure_tensor(indices)
    k = _norm_tuple(kernel_size, 2)
    s = _norm_tuple(stride if stride is not None else kernel_size, 2)
    n, c, oh, ow = x._value.shape
    if output_size is not None:
        H, W = int(output_size[-2]), int(output_size[-1])
    else:
        H = (oh - 1) * s[0] + k[0] - 2 * _norm_tuple(padding, 2)[0]
        W = (ow - 1) * s[1] + k[1] - 2 * _norm_tuple(padding, 2)[1]
    iv = indices._value.astype(jnp.int32)

    def fn(v):
        flat = jnp.zeros((n, c, H * W), v.dtype)
        nidx = jnp.arange(n)[:, None, None]
        cidx = jnp.arange(c)[None, :, None]
        # set, not add: overlapping windows (stride < kernel) can share
        # one argmax position and must place the value once
        flat = flat.at[nidx, cidx, iv.reshape(n, c, -1)].set(
            v.reshape(n, c, -1), mode="drop")
        return flat.reshape(n, c, H, W)

    return apply(fn, x)
