"""Loss functionals.

Parity: `python/paddle/nn/functional/loss.py` (reference kernels
`operators/softmax_with_cross_entropy_op.cu`, `bce_loss_op.cu`,
`smooth_l1_loss_op.cc`, warpctc `operators/warpctc_op.cc`). CTC uses an
in-framework lax.scan forward algorithm (no warpctc on TPU).
"""
import numpy as np
import jax
import jax.numpy as jnp
import jax.nn as jnn

from ...core.tensor import Tensor, apply
from ...tensor._helpers import ensure_tensor


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def _log_softmax_amp(lg, ax, op):
    """log_softmax whose SUM accumulates in the amp-list dtype for `op`
    (f32 for black ops — the default — without materializing an f32 copy
    of the logits; bf16 end-to-end if the user white-lists the op)."""
    from ...amp import amp_op_dtype, amp_state
    acc = amp_op_dtype(op, lg.dtype)
    if not amp_state().enabled or acc == lg.dtype:
        return jnn.log_softmax(lg, axis=ax)
    m = jax.lax.stop_gradient(jnp.max(lg, axis=ax, keepdims=True))
    s = jnp.sum(jnp.exp(lg - m), axis=ax, keepdims=True, dtype=acc)
    return lg - m - jnp.log(s).astype(lg.dtype)


def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    input = ensure_tensor(input)  # noqa: A001
    label = ensure_tensor(label)
    lv = label._value
    wv = ensure_tensor(weight)._value if weight is not None else None

    def fn(logits):
        ax = axis % logits.ndim
        logp = _log_softmax_amp(logits, ax, "cross_entropy") \
            if use_softmax else jnp.log(jnp.maximum(logits, 1e-30))
        if soft_label:
            tgt = lv.astype(logp.dtype)
            if label_smoothing > 0:
                k = logits.shape[ax]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            # vocab-sized reduction: accumulate f32 off bf16 operands
            loss = -jnp.sum(tgt * logp, axis=ax, dtype=jnp.float32)
            if reduction == "mean":
                return jnp.mean(loss)
            return _reduce(loss, reduction)
        idx = lv.astype(jnp.int32)
        squeeze = False
        if idx.ndim == logits.ndim and idx.shape[ax] == 1:
            idx = jnp.squeeze(idx, axis=ax)
            squeeze = True
        valid = idx != ignore_index
        safe_idx = jnp.where(valid, idx, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe_idx, ax), axis=ax)
        picked = jnp.squeeze(picked, axis=ax)
        if label_smoothing > 0:
            k = logits.shape[ax]
            smooth = jnp.mean(logp, axis=ax, dtype=jnp.float32)
            loss = -((1 - label_smoothing) * picked.astype(jnp.float32)
                     + label_smoothing * smooth)
        else:
            loss = -picked
        # the per-token losses are tiny [N]; summing them in the logits
        # dtype (bf16 under amp) loses ~2 decimal digits over 16k tokens
        loss = loss.astype(jnp.float32)
        if wv is not None:
            w = jnp.take(wv.astype(loss.dtype), safe_idx)
            loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if wv is not None:
                denom = jnp.sum(jnp.where(valid, jnp.take(
                    wv.astype(loss.dtype), safe_idx), 0.0))
            else:
                denom = jnp.sum(valid.astype(loss.dtype))
            return jnp.sum(loss) / jnp.maximum(denom, 1.0)
        return _reduce(loss, reduction)
    return apply(fn, input)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    """Reference `operators/softmax_with_cross_entropy_op.cu`; returns
    per-example loss with trailing 1-dim kept, like the reference."""
    logits = ensure_tensor(logits)
    label = ensure_tensor(label)
    lv = label._value

    def fn(lg):
        ax = axis % lg.ndim
        logp = _log_softmax_amp(lg, ax, "softmax_with_cross_entropy")
        if soft_label:
            loss = -jnp.sum(lv.astype(logp.dtype) * logp, axis=ax,
                            keepdims=True, dtype=jnp.float32)
        else:
            idx = lv.astype(jnp.int32)
            if idx.ndim == lg.ndim and idx.shape[ax] == 1:
                picked = jnp.take_along_axis(logp, idx, axis=ax)
            else:
                picked = jnp.take_along_axis(
                    logp, jnp.expand_dims(jnp.where(idx == ignore_index, 0, idx), ax), axis=ax)
            loss = -picked
            mask_idx = idx if idx.ndim == loss.ndim else jnp.expand_dims(idx, ax)
            loss = jnp.where(mask_idx == ignore_index, 0.0, loss)
        if return_softmax:
            return loss, jnn.softmax(lg, axis=ax)
        return loss
    out = apply(fn, logits)
    return out


def nll_loss(input, label, weight=None, ignore_index=-100,  # noqa: A002
             reduction="mean", name=None):
    input = ensure_tensor(input)  # noqa: A001
    label = ensure_tensor(label)
    lv = label._value.astype(jnp.int32)
    wv = ensure_tensor(weight)._value if weight is not None else None

    def fn(logp):
        valid = lv != ignore_index
        safe = jnp.where(valid, lv, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        loss = -jnp.squeeze(picked, axis=1)
        w = jnp.take(wv.astype(loss.dtype), safe) if wv is not None else \
            jnp.ones_like(loss)
        loss = jnp.where(valid, loss * w, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
        return _reduce(loss, reduction)
    return apply(fn, input)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)  # noqa: A001
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction),
                 input, label)


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)  # noqa: A001
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)  # noqa: A001

    def fn(a, b):
        d = a - b
        ad = jnp.abs(d)
        loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        # paddle's smooth_l1_loss multiplies by delta
        return _reduce(loss * delta, reduction)
    return apply(fn, input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean",  # noqa: A002
                         name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)  # noqa: A001
    wv = ensure_tensor(weight)._value if weight is not None else None

    def fn(p, t):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))
        if wv is not None:
            loss = loss * wv
        return _reduce(loss, reduction)
    return apply(fn, input, label)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)
    wv = ensure_tensor(weight)._value if weight is not None else None
    pw = ensure_tensor(pos_weight)._value if pos_weight is not None else None

    def fn(z, t):
        if pw is not None:
            base = -(pw * t * jnn.log_sigmoid(z)
                     + (1 - t) * jnn.log_sigmoid(-z))
        else:
            # numerically stable: max(z,0) - z*t + log(1+exp(-|z|))
            base = jnp.maximum(z, 0) - z * t + jnn.softplus(-jnp.abs(z))
        if wv is not None:
            base = base * wv
        return _reduce(base, reduction)
    return apply(fn, logit, label)


def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)  # noqa: A001

    def fn(logp, t):
        loss = t * (jnp.log(jnp.maximum(t, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply(fn, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    input, other, label = (ensure_tensor(input), ensure_tensor(other),  # noqa: A001
                           ensure_tensor(label))
    return apply(lambda a, b, t: _reduce(
        jnp.maximum(0.0, -t * (a - b) + margin), reduction), input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)  # noqa: A001
    return apply(lambda a, t: _reduce(
        jnp.where(t == 1.0, a, jnp.maximum(0.0, margin - a)), reduction),
        input, label)


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    input1, input2, label = (ensure_tensor(input1), ensure_tensor(input2),
                             ensure_tensor(label))

    def fn(a, b, t):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(t == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply(fn, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    input, positive, negative = (ensure_tensor(input), ensure_tensor(positive),  # noqa: A001
                                 ensure_tensor(negative))

    def fn(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.abs(a - pos) ** p, axis=-1) + epsilon, 1 / p)
        dn = jnp.power(jnp.sum(jnp.abs(a - neg) ** p, axis=-1) + epsilon, 1 / p)
        if swap:
            dsn = jnp.power(jnp.sum(jnp.abs(pos - neg) ** p, axis=-1) + epsilon,
                            1 / p)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply(fn, input, positive, negative)


def square_error_cost(input, label):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)  # noqa: A001
    return apply(lambda a, b: jnp.square(a - b), input, label)


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)  # noqa: A001
    return apply(lambda p, t: -t * jnp.log(p + epsilon)
                 - (1 - t) * jnp.log(1 - p + epsilon), input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)
    nv = ensure_tensor(normalizer)._value if normalizer is not None else None

    def fn(z, t):
        p = jnn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * t + jnn.softplus(-jnp.abs(z))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if nv is not None:
            loss = loss / nv
        return _reduce(loss, reduction)
    return apply(fn, logit, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False,
             norm_by_batchsize=False, norm_by_total_logits_len=False):
    """CTC via lax.scan dynamic programming — TPU-native replacement for
    warpctc (`operators/warpctc_op.cc`). log_probs: [T, B, C] (paddle layout);
    labels: [B, L] int padded. The three norm_* switches mirror the
    reference warpctc attrs: per-sequence-length, per-batch-size, or
    per-total-logit-length scaling of the per-example loss (mutually
    exclusive in the reference; first true one wins here in the same
    precedence order)."""
    log_probs = ensure_tensor(log_probs)
    labels_v = ensure_tensor(labels)._value.astype(jnp.int32)
    in_len = ensure_tensor(input_lengths)._value.astype(jnp.int32).reshape(-1)
    lb_len = ensure_tensor(label_lengths)._value.astype(jnp.int32).reshape(-1)

    def fn(lp):
        lp = jnn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        L = labels_v.shape[1]
        S = 2 * L + 1
        # extended label sequence: blank t1 blank t2 ... blank
        ext = jnp.full((B, S), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(labels_v)
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        alpha0 = jnp.full((B, S), neg_inf, dtype=lp.dtype)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), blank])
        first_lab = jnp.where(lb_len > 0, labels_v[:, 0], blank)
        alpha0 = alpha0.at[:, 1].set(jnp.where(
            lb_len > 0, lp[0, jnp.arange(B), first_lab], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), dtype=jnp.bool_),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf, lp.dtype), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf, lp.dtype), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_body(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            # freeze once past input length
            alpha = jnp.where((t < in_len)[:, None], new_alpha, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_body, alpha0, jnp.arange(1, T))
        idx_last = 2 * lb_len
        idx_prev = jnp.maximum(2 * lb_len - 1, 0)
        ll = jnp.logaddexp(
            jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0],
            jnp.where(lb_len > 0,
                      jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0],
                      neg_inf))
        loss = -ll
        if norm_by_times:
            loss = loss / jnp.maximum(in_len.astype(loss.dtype), 1)
        elif norm_by_batchsize:
            loss = loss / loss.shape[0]
        elif norm_by_total_logits_len:
            loss = loss / jnp.maximum(
                jnp.sum(in_len).astype(loss.dtype), 1)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lb_len.astype(loss.dtype), 1))
        return _reduce(loss, reduction)
    return apply(fn, log_probs)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    anchor, positive = ensure_tensor(anchor), ensure_tensor(positive)
    labels = ensure_tensor(labels)

    def fn(a, p):
        lv = labels._value.reshape(-1)
        sim = jnp.matmul(a, p.T)
        tgt = (lv[:, None] == lv[None, :]).astype(sim.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logp = jnn.log_softmax(sim, axis=1)
        xent = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(a), axis=1)) +
                        jnp.mean(jnp.sum(jnp.square(p), axis=1))) * 0.25
        return xent + reg
    return apply(fn, anchor, positive)


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """Dice loss for segmentation (reference `nn/functional/loss.py`
    dice_loss): 1 - 2*|X∩Y| / (|X|+|Y|), reduced over all but batch."""
    input = ensure_tensor(input)  # noqa: A001
    lv = ensure_tensor(label)._value

    def fn(p):
        oh = jax.nn.one_hot(jnp.squeeze(lv, -1).astype(jnp.int32),
                            p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * oh, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(oh, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return apply(fn, input)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over a complete binary tree (reference
    `operators/hierarchical_sigmoid_op.cc` default mode; the custom-tree
    path_table/path_code inputs select per-sample node paths)."""
    input = ensure_tensor(input)  # noqa: A001
    weight = ensure_tensor(weight)
    lv = ensure_tensor(label)._value.astype(jnp.int32).reshape(-1)
    code_len = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))

    if path_table is not None:
        # paddle custom-tree contract: PER-SAMPLE rows [N, L]
        tb = jnp.asarray(ensure_tensor(path_table)._value, jnp.int32)
        cd = jnp.asarray(ensure_tensor(path_code)._value, jnp.float32)
    else:
        # complete-binary-tree codes for each class id: node indices and
        # left/right bits from the root
        tables, codes = [], []
        for c in range(num_classes):
            node = c + num_classes - 1   # leaf position in the heap
            t, b = [], []
            while node > 0:
                parent = (node - 1) // 2
                t.append(parent)
                b.append(float(node == 2 * parent + 2))  # right child -> 1
                node = parent
            t = t[::-1][:code_len]
            b = b[::-1][:code_len]
            pad = code_len - len(t)
            tables.append(t + [0] * pad)
            codes.append(b + [-1.0] * pad)   # -1 marks padding
        table_np = np.asarray(tables, np.int32)
        code_np = np.asarray(codes, np.float32)
        tb = jnp.asarray(table_np)[lv]   # [N, L] node ids per sample
        cd = jnp.asarray(code_np)[lv]    # [N, L] bits (-1 padding)

    def fn(x, w, *b):
        logits = jnp.einsum("bd,bld->bl", x, w[tb])
        if b:
            logits = logits + b[0].reshape(-1)[tb]
        valid = cd >= 0
        # sigmoid CE with target = bit; paddle returns the per-sample
        # path sum with shape [N, 1] (no batch reduction)
        ce = jnp.maximum(logits, 0) - logits * cd + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.sum(jnp.where(valid, ce, 0.0), axis=1,
                       keepdims=True)

    tensors = [input, weight]
    if bias is not None:
        tensors.append(ensure_tensor(bias))
    return apply(fn, *tensors)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-style margin softmax (reference
    `operators/margin_cross_entropy_op.cu`): the target-class logit
    cos(theta) becomes cos(margin1*theta + margin2) - margin3, then
    scaled softmax CE. Single-group (non-model-parallel) semantics; the
    class-parallel sharding composes via mp_layers.ParallelCrossEntropy."""
    logits = ensure_tensor(logits)
    lv = ensure_tensor(label)._value.astype(jnp.int32).reshape(-1)

    def fn(lg):
        n, c = lg.shape
        onehot = jax.nn.one_hot(lv, c, dtype=lg.dtype)
        # keep cos strictly inside (-1, 1): d/dx arccos is inf at +-1 and
        # the inf poisons grads through where() (inf * 0 = NaN)
        eps = 1e-6
        cos = jnp.clip(lg, -1.0 + eps, 1.0 - eps)
        theta = jnp.arccos(cos)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        adj = jnp.where(onehot > 0, target, cos) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        loss = -jnp.sum(onehot * logp, axis=-1)
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jax.nn.softmax(adj, axis=-1)
        return loss

    return apply(fn, logits)
