"""Convolution functionals on `lax.conv_general_dilated`.

Parity: `python/paddle/nn/functional/conv.py` (reference: cudnn conv kernels
`operators/conv_cudnn_op.cu`, `conv_op.cc`, `conv_transpose_op.cc`). One lax
primitive covers every case (groups/dilation/stride); XLA tiles it onto the
MXU — the reference's algo-search machinery (`conv_search_cache.h`) has no
TPU analog because the compiler picks the schedule.

Weight layout follows paddle: [out_c, in_c/groups, *spatial].
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply
from ...tensor._helpers import ensure_tensor


def _norm_tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _norm_padding(padding, n, strides=None):
    """paddle padding: int, list[n], list[2n], pairs, or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # may include batch/channel dims (paddle 4-elem pair form)
        pads = [tuple(p) for p in padding]
        if len(pads) == n + 2:
            pads = pads[2:]
        return pads
    return [(int(p), int(p)) for p in padding]


def _dim_numbers(nd, channel_last):
    if nd == 1:
        return ("NCH", "OIH", "NCH") if not channel_last else ("NHC", "OIH", "NHC")
    if nd == 2:
        return ("NCHW", "OIHW", "NCHW") if not channel_last else ("NHWC", "OIHW", "NHWC")
    return ("NCDHW", "OIDHW", "NCDHW") if not channel_last else ("NDHWC", "OIDHW", "NDHWC")


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd,
             channel_last):
    from ...amp import maybe_cast_to_compute as _amp
    stride = _norm_tuple(stride, nd)
    dilation = _norm_tuple(dilation, nd)
    pad = _norm_padding(padding, nd)
    dn = _dim_numbers(nd, channel_last)

    def fn(v, w):
        v, w = _amp(v, "conv"), _amp(w, "conv")
        return lax.conv_general_dilated(
            v, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)

    out = apply(fn, x, weight)
    if bias is not None:
        bias = ensure_tensor(bias)
        ch_axis = (nd + 1) if channel_last else 1
        bshape = [1] * (nd + 2)
        bshape[ch_axis] = -1

        def addb(o, b):
            return o + b.reshape(bshape)
        out = apply(addb, out, bias)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(ensure_tensor(x), ensure_tensor(weight), bias, stride,
                    padding, dilation, groups, 1, data_format == "NLC")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(ensure_tensor(x), ensure_tensor(weight), bias, stride,
                    padding, dilation, groups, 2, data_format == "NHWC")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(ensure_tensor(x), ensure_tensor(weight), bias, stride,
                    padding, dilation, groups, 3, data_format == "NDHWC")


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, nd, channel_last, output_size=None):
    stride = _norm_tuple(stride, nd)
    dilation = _norm_tuple(dilation, nd)
    pad = _norm_padding(padding, nd)
    opad = _norm_tuple(output_padding, nd)
    dn = _dim_numbers(nd, channel_last)

    # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
    def fn(v, w):
        if isinstance(pad, str):
            pads = pad
        else:
            # transposed conv: effective padding = k - 1 - p (with dilation)
            pads = []
            for i in range(nd):
                k = (w.shape[2 + i] - 1) * dilation[i] + 1
                lo = k - 1 - pad[i][0]
                hi = k - 1 - pad[i][1] + opad[i]
                pads.append((lo, hi))
        # grouped transpose: split in feature groups
        if groups == 1:
            wt = jnp.swapaxes(w, 0, 1)  # -> [out_c, in_c, *k]
            wt = jnp.flip(wt, axis=tuple(range(2, 2 + nd)))
            return lax.conv_general_dilated(
                v, wt, window_strides=(1,) * nd, padding=pads,
                lhs_dilation=stride, rhs_dilation=dilation,
                dimension_numbers=dn)
        vs = jnp.split(v, groups, axis=1 if not channel_last else nd + 1)
        ws = jnp.split(w, groups, axis=0)
        outs = []
        for vi, wi in zip(vs, ws):
            wt = jnp.swapaxes(wi, 0, 1)
            wt = jnp.flip(wt, axis=tuple(range(2, 2 + nd)))
            outs.append(lax.conv_general_dilated(
                vi, wt, window_strides=(1,) * nd, padding=pads,
                lhs_dilation=stride, rhs_dilation=dilation,
                dimension_numbers=dn))
        return jnp.concatenate(outs, axis=1 if not channel_last else nd + 1)

    out = apply(fn, x, weight)
    if output_size is not None:
        pass  # shapes already determined by padding math
    if bias is not None:
        bias = ensure_tensor(bias)
        ch_axis = (nd + 1) if channel_last else 1
        bshape = [1] * (nd + 2)
        bshape[ch_axis] = -1
        out = apply(lambda o, b: o + b.reshape(bshape), out, bias)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose_nd(ensure_tensor(x), ensure_tensor(weight), bias,
                              stride, padding, output_padding, dilation,
                              groups, 1, False, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, output_size=None,
                     data_format="NCHW", name=None):
    # param ORDER follows the reference (`nn/functional/conv.py`:
    # dilation before groups) for positional users
    return _conv_transpose_nd(ensure_tensor(x), ensure_tensor(weight), bias,
                              stride, padding, output_padding, dilation,
                              groups, 2, data_format == "NHWC", output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose_nd(ensure_tensor(x), ensure_tensor(weight), bias,
                              stride, padding, output_padding, dilation,
                              groups, 3, data_format == "NDHWC", output_size)
