"""paddle.nn.utils — weight reparameterization hooks.

Reference surface: `python/paddle/nn/utils/__init__.py`
(`weight_norm_hook.py`, `spectral_norm_hook.py`). Same mechanism here:
the original parameter is removed from the layer's parameter dict,
replaced by the reparameterized pieces, and a forward-pre-hook
recomputes the effective weight each call — so the recomputation is
part of the traced program and gradients flow to the pieces in both
eager and jit regimes.
"""
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, Parameter, apply

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm"]


def _norm_except(v, dim):
    """L2 norm over every axis except `dim` (paddle weight_norm's g
    shape: [v.shape[dim]])."""
    axes = tuple(a for a in range(v.ndim) if a != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes))


def _compute_weight_wn(g, v, dim):
    def fn(gv, vv):
        n = _norm_except(vv, dim)
        shape = [1] * vv.ndim
        shape[dim] = -1
        return vv * (gv / jnp.maximum(n, 1e-12)).reshape(shape)
    return apply(fn, g, v)


def weight_norm(layer, name="weight", dim=0):
    """w = g * v/||v|| (reference `weight_norm_hook.py` weight_norm).
    dim=None means a single scalar g over the whole tensor."""
    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"{type(layer).__name__}.{name} is None")
    eff_dim = 0 if dim is None else dim
    if eff_dim < 0:
        eff_dim += w.ndim
    wv = w._value
    if dim is None:
        g0 = jnp.sqrt(jnp.sum(jnp.square(wv))).reshape(1)
    else:
        g0 = _norm_except(wv, eff_dim)
    del layer._parameters[name]
    g = Parameter(g0, name=f"{name}_g")
    v = Parameter(wv, name=f"{name}_v")
    layer.add_parameter(f"{name}_g", g)
    layer.add_parameter(f"{name}_v", v)

    def hook(lyr, inputs):
        gp = lyr._parameters[f"{name}_g"]
        vp = lyr._parameters[f"{name}_v"]
        if dim is None:
            def fn(gv, vv):
                n = jnp.sqrt(jnp.sum(jnp.square(vv)))
                return vv * (gv[0] / jnp.maximum(n, 1e-12))
            w_eff = apply(fn, gp, vp)
        else:
            w_eff = _compute_weight_wn(gp, vp, eff_dim)
        object.__setattr__(lyr, name, w_eff)

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (handle, dim)
    hook(layer, ())   # effective weight available immediately
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a plain parameter and drop the hook."""
    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"no weight_norm hook on {name!r}")
    handle, dim = hooks.pop(name)
    handle.remove()
    g = layer._parameters.pop(f"{name}_g")
    v = layer._parameters.pop(f"{name}_v")
    eff_dim = 0 if dim is None else dim
    if eff_dim < 0:
        eff_dim += v.ndim
    if dim is None:
        n = jnp.sqrt(jnp.sum(jnp.square(v._value)))
        w = v._value * (g._value[0] / jnp.maximum(n, 1e-12))
    else:
        n = _norm_except(v._value, eff_dim)
        shape = [1] * v.ndim
        shape[eff_dim] = -1
        w = v._value * (g._value / jnp.maximum(n, 1e-12)).reshape(shape)
    if name in layer.__dict__:
        del layer.__dict__[name]
    layer.add_parameter(name, Parameter(w, name=name))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """w_sn = w / sigma_max(w) via power iteration (reference
    `spectral_norm_hook.py`). The u/v estimate vectors live as
    non-trainable buffers updated on every forward, exactly like the
    reference hook."""
    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"{type(layer).__name__}.{name} is None")
    if dim is None:
        # reference default (spectral_norm_hook.py:202-207): dim=1 for
        # Linear and the transposed convs (their weight is [in, out, ...]),
        # else 0
        dim = 1 if type(layer).__name__ in (
            "Linear", "Conv1DTranspose", "Conv2DTranspose",
            "Conv3DTranspose") else 0
    if dim < 0:
        dim += w.ndim
    wv = w._value
    h = wv.shape[dim]
    del layer._parameters[name]
    orig = Parameter(wv, name=f"{name}_orig")
    layer.add_parameter(f"{name}_orig", orig)
    import numpy as _np
    rs = _np.random.RandomState(0)
    u0 = rs.randn(h).astype(_np.float32)
    u0 /= max(float(_np.linalg.norm(u0)), eps)
    layer.register_buffer(f"{name}_u", Tensor(jnp.asarray(u0)),
                          persistable=True)

    def hook(lyr, inputs):
        wp = lyr._parameters[f"{name}_orig"]
        u_buf = lyr._buffers[f"{name}_u"]

        # reference gates iteration on training
        # (spectral_norm_hook.py:92 do_power_iteration): in eval the
        # stored estimate is used as-is so repeated inference is pure
        iters = max(1, n_power_iterations) if lyr.training else 0

        def fn(wval, uval):
            mat = jnp.moveaxis(wval, dim, 0).reshape(h, -1)
            u = uval.astype(jnp.float32)
            for _ in range(iters):
                v = mat.T.astype(jnp.float32) @ u
                v = v / jnp.maximum(jnp.linalg.norm(v), eps)
                u = mat.astype(jnp.float32) @ v
                u = u / jnp.maximum(jnp.linalg.norm(u), eps)
            v = mat.T.astype(jnp.float32) @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            # u/v are treated as constants for the gradient, like the
            # reference hook's detached estimates
            u = jax.lax.stop_gradient(u)
            v = jax.lax.stop_gradient(v)
            sigma = u @ (mat.astype(jnp.float32) @ v)
            return (wval / sigma.astype(wval.dtype)), u

        w_eff, u_new = apply(fn, wp, u_buf)
        if lyr.training:
            # in-place value update, same pattern as batch_norm's running
            # stats: the buffer OBJECT stays in _buffers so TrainStep's
            # buffer-carry tracking picks the new value up under jit
            u_buf._value = u_new._value
        object.__setattr__(lyr, name, w_eff)

    handle = layer.register_forward_pre_hook(hook)
    layer._spectral_norm_hooks = getattr(layer, "_spectral_norm_hooks", {})
    layer._spectral_norm_hooks[name] = handle
    hook(layer, ())
    return layer
