"""Parameter initializers.

Parity: `python/paddle/nn/initializer/` and `python/paddle/fluid/initializer.py`
in the reference. Initializers are pure functions shape×dtype→array drawing
from the global Generator (`core.random`).
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.random import next_key
from ...core.dtype import convert_dtype, get_default_dtype


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weight layout [out_c, in_c/groups, *k]
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(tuple(shape), self.value,
                        dtype=convert_dtype(dtype) or get_default_dtype())


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dt = convert_dtype(dtype) or get_default_dtype()
        return self.mean + self.std * jax.random.normal(
            next_key(), tuple(shape)).astype(dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dt = convert_dtype(dtype) or get_default_dtype()
        return (self.mean + self.std * jax.random.truncated_normal(
            next_key(), -2.0, 2.0, tuple(shape))).astype(dt)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        dt = convert_dtype(dtype) or get_default_dtype()
        return jax.random.uniform(next_key(), tuple(shape), minval=self.low,
                                  maxval=self.high).astype(dt)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, name=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        dt = convert_dtype(dtype) or get_default_dtype()
        return jax.random.uniform(next_key(), tuple(shape), minval=-limit,
                                  maxval=limit).astype(dt)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, name=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        dt = convert_dtype(dtype) or get_default_dtype()
        return (std * jax.random.normal(next_key(), tuple(shape))).astype(dt)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _gain(self):
        if self.nonlinearity == "leaky_relu":
            return math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        return math.sqrt(2.0)

    def __call__(self, shape, dtype=None):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        limit = self._gain() * math.sqrt(3.0 / fi)
        dt = convert_dtype(dtype) or get_default_dtype()
        return jax.random.uniform(next_key(), tuple(shape), minval=-limit,
                                  maxval=limit).astype(dt)


class KaimingNormal(KaimingUniform):
    def __call__(self, shape, dtype=None):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        std = self._gain() / math.sqrt(fi)
        dt = convert_dtype(dtype) or get_default_dtype()
        return (std * jax.random.normal(next_key(), tuple(shape))).astype(dt)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype=None):
        from ...core.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(v, dtype=convert_dtype(dtype) or None)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(tuple(shape))
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        dt = convert_dtype(dtype) or get_default_dtype()
        init = jax.nn.initializers.orthogonal(scale=self.gain)
        return init(next_key(), tuple(shape)).astype(dt)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        dt = convert_dtype(dtype) or get_default_dtype()
        init = jax.nn.initializers.delta_orthogonal()
        try:
            return init(next_key(), tuple(shape)).astype(dt)
        except Exception:
            w = np.zeros(shape, dtype=np.float32)
            oc, ic = shape[0], shape[1]
            centers = tuple(s // 2 for s in shape[2:])
            for i in range(min(oc, ic * self.groups)):
                w[(i, i % ic) + centers] = 1.0
            return jnp.asarray(w, dtype=dt)


# paddle aliases
GlorotUniform = XavierUniform
GlorotNormal = XavierNormal
MSRAUniform = KaimingUniform
MSRANormal = KaimingNormal


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "selu": 3.0 / 4.0}
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    return gains.get(nonlinearity, 1.0)


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference
    `nn/initializer/Bilinear` / `fluid/initializer.py BilinearInitializer`):
    weight [C_out, C_in, kh, kw] filled with the bilinear interpolation
    kernel on each spatial slice."""

    def __call__(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D conv "
                             f"weight, got shape {shape}")
        kh, kw = shape[2], shape[3]
        fh, fw = (kh + 1) // 2, (kw + 1) // 2
        ch = (2 * fh - 1 - fh % 2) / (2.0 * fh)
        cw = (2 * fw - 1 - fw % 2) / (2.0 * fw)
        y = (1 - np.abs(np.arange(kh) / fh - ch))[:, None]
        x = (1 - np.abs(np.arange(kw) / fw - cw))[None, :]
        kern = (y * x).astype(np.float32)
        w = np.zeros(shape, np.float32)
        w[:, :] = kern
        return jnp.asarray(w, dtype)


_GLOBAL_WEIGHT_INIT = None
_GLOBAL_BIAS_INIT = None


def set_global_initializer(weight_init, bias_init=None):
    """Reference `fluid/initializer.py set_global_initializer`: override
    the default initializer Layers use when neither param attr nor call
    site specifies one. Pass (None, None) to reset."""
    global _GLOBAL_WEIGHT_INIT, _GLOBAL_BIAS_INIT
    _GLOBAL_WEIGHT_INIT = weight_init
    _GLOBAL_BIAS_INIT = bias_init


def _global_default(is_bias):
    return _GLOBAL_BIAS_INIT if is_bias else _GLOBAL_WEIGHT_INIT
