"""paddle_tpu.nn — mirrors `python/paddle/nn/__init__.py`."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401

from .layer.layers import Layer, ParamAttr  # noqa: F401
from .layer.container import (  # noqa: F401
    Sequential, LayerList, LayerDict, ParameterList,
)
from .layer.common import (  # noqa: F401
    Identity, Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    Flatten, Pad1D, Pad2D, Pad3D, ZeroPad2D, Upsample, UpsamplingNearest2D,
    UpsamplingBilinear2D, Bilinear, CosineSimilarity, PairwiseDistance,
    Unfold, Fold,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
)
from .layer.activation import (  # noqa: F401
    ReLU, ReLU6, Sigmoid, Tanh, Silu, Swish, Mish, Softsign, Tanhshrink,
    LogSigmoid, Hardswish, Hardsigmoid, Softplus, ThresholdedReLU, GELU,
    LeakyReLU, ELU, SELU, CELU, Hardtanh, Hardshrink, Softshrink, PReLU,
    RReLU, Maxout, Softmax, LogSoftmax,
)
from .layer.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D, MaxUnPool2D,
)
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, MarginRankingLoss, CTCLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss,
 HSigmoidLoss,
)
from .layer.rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN,
    LSTM, GRU,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm, clip_grad_norm_,
)
from .layer.vision import PixelShuffle, PixelUnshuffle, ChannelShuffle  # noqa: F401

from ..generation import BeamSearchDecoder  # noqa: F401,E402

from ..generation import dynamic_decode  # noqa: F401,E402
