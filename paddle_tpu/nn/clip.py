"""Gradient clipping.

Parity: `python/paddle/fluid/clip.py` (ClipGradByValue/Norm/GlobalNorm).
Operates on (param, grad) lists like the reference; used by Optimizer before
the update step. Under hybrid parallelism, `distributed.HybridParallelClipGrad`
wraps GlobalNorm to sum norms across mesh axes.
"""
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..tensor._helpers import ensure_tensor


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = max
        self.min = -max if min is None else min

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, apply(
                lambda v: jnp.clip(v, self.min, self.max), ensure_tensor(g))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            cn = self.clip_norm

            def fn(v):
                norm = jnp.sqrt(jnp.sum(jnp.square(v)))
                return jnp.where(norm > cn, v * (cn / jnp.maximum(norm, 1e-12)), v)
            out.append((p, apply(fn, ensure_tensor(g))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def _compute_global_norm_sq(self, grads):
        sq = None
        for g in grads:
            s = jnp.sum(jnp.square(g._value.astype(jnp.float32)))
            sq = s if sq is None else sq + s
        return sq

    def _dygraph_clip(self, params_grads):
        grads = [ensure_tensor(g) for p, g in params_grads
                 if g is not None and getattr(p, "need_clip", True)]
        if not grads:
            return params_grads
        global_sq = self._compute_global_norm_sq(grads)
        global_norm = jnp.sqrt(global_sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            g = ensure_tensor(g)
            out.append((p, apply(lambda v: v * scale.astype(v.dtype), g)))
        return out


GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._value) ** norm_type) for g in grads]))
        total = total ** (1.0 / norm_type)
    coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._value = p.grad._value * coef.astype(p.grad._value.dtype)
    return Tensor(total)
