"""Normalization layers. Parity: `python/paddle/nn/layer/norm.py`.

SyncBatchNorm: on TPU, batch-norm stats inside a pjit'd step over a dp-sharded
batch are automatically global (XLA inserts the cross-replica reductions for
the mean/var reduces under GSPMD) — so SyncBatchNorm == BatchNorm here, unlike
the reference's dedicated `sync_batch_norm_op.cu` NCCL kernel.
"""
import numpy as np
import jax.numpy as jnp

from .layers import Layer
from ..initializer import Constant
from .. import functional as F
from ...core.tensor import Tensor


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self._mean = self.register_buffer(
            "_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self._variance = self.register_buffer(
            "_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (`python/paddle/fluid/dygraph/nn.py` BatchNorm):
    acts like 2.x BatchNorm but defaults in_place semantics."""

    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-05, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        # is_test=True == reference inference mode: normalize with the
        # running statistics regardless of Layer.training
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         (use_global_stats or is_test) or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """See module docstring: GSPMD makes plain BN sync across dp shards."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers["_mean"] = layer._mean
            new._buffers["_variance"] = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self._power_iters = power_iters
        self._eps = eps
        self._dim = dim
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..initializer import Normal
        self.weight_u = self.create_parameter(
            [h], default_initializer=Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.tensor import apply
        dim, eps, iters = self._dim, self._eps, self._power_iters
        uv, vv = self.weight_u._value, self.weight_v._value

        def fn(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = uv, vv
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma
        return apply(fn, weight)
