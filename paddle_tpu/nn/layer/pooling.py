"""Pooling layers. Parity: `python/paddle/nn/layer/pooling.py`."""
from .layers import Layer
from .. import functional as F


class _Pool(Layer):
    def __init__(self, kernel_size=None, stride=None, padding=0, **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kw = kw


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding)
        self.return_mask = return_mask
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, data_format=self.data_format)


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding)


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__(kernel_size, stride, padding)
        self.exclusive = exclusive
        self.divisor_override = divisor_override
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            exclusive=self.exclusive,
                            divisor_override=self.divisor_override,
                            data_format=self.data_format)


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size)


class MaxUnPool2D(Layer):
    """Reference `nn/layer/pooling.py` MaxUnPool2D over F.max_unpool2d."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._kernel = kernel_size
        self._stride = stride
        self._padding = padding
        self._format = data_format
        self._output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self._kernel, self._stride,
                              self._padding, self._format,
                              self._output_size)
