"""Recurrent layers via `lax.scan`.

Parity: `python/paddle/nn/layer/rnn.py` (reference: `operators/rnn_op.h`,
cudnn LSTM/GRU kernels). TPU-native: the time loop is a lax.scan (one compiled
step reused per timestep — XLA unrolls nothing, keeping compile time flat) and
the gate matmuls are batched MXU ops. Gate order follows paddle:
LSTM [i, f, c(g), o]; GRU [r, u(z), c(n)] with the cudnn-style
"reset-after-matmul" candidate.
"""
import math

import jax
import jax.numpy as jnp

from .layers import Layer
from ..initializer import Uniform
from ...core.tensor import Tensor, apply
from ...tensor._helpers import ensure_tensor


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = ensure_tensor(batch_ref)._value.shape[batch_dim_idx]
        return Tensor(jnp.full((batch, self.hidden_size), init_value,
                               jnp.float32))


def _cell_params(layer, input_size, hidden_size, n_gates, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
    std = 1.0 / math.sqrt(hidden_size)
    u = Uniform(-std, std)
    layer.weight_ih = layer.create_parameter(
        [n_gates * hidden_size, input_size], attr=weight_ih_attr,
        default_initializer=u)
    layer.weight_hh = layer.create_parameter(
        [n_gates * hidden_size, hidden_size], attr=weight_hh_attr,
        default_initializer=u)
    layer.bias_ih = layer.create_parameter(
        [n_gates * hidden_size], attr=bias_ih_attr, is_bias=True,
        default_initializer=u)
    layer.bias_hh = layer.create_parameter(
        [n_gates * hidden_size], attr=bias_hh_attr, is_bias=True,
        default_initializer=u)


def _lstm_step(x, h, c, wih, whh, bih, bhh, hidden_size):
    gates = x @ wih.T + bih + h @ whh.T + bhh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    # cast back to the carry dtype: under AMP, bf16 x against f32 weights
    # promotes the gates to f32, and a scan carry must keep its dtype
    return h_new.astype(h.dtype), c_new.astype(c.dtype)


def _gru_step(x, h, wih, whh, bih, bhh, hidden_size):
    xg = x @ wih.T + bih
    hg = h @ whh.T + bhh
    xr, xz, xn = jnp.split(xg, 3, axis=-1)
    hr, hz, hn = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return ((1.0 - z) * n + z * h).astype(h.dtype)


def _simple_step(x, h, wih, whh, bih, bhh, hidden_size, activation="tanh"):
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    return act(x @ wih.T + bih + h @ whh.T + bhh).astype(h.dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        _cell_params(self, input_size, hidden_size, 1, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = apply(lambda x, h, wih, whh, bih, bhh: _simple_step(
            x, h, wih, whh, bih, bhh, self.hidden_size, self.activation),
            ensure_tensor(inputs), ensure_tensor(states), self.weight_ih,
            self.weight_hh, self.bias_ih, self.bias_hh)
        return out, out

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        _cell_params(self, input_size, hidden_size, 4, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states
        hn, cn = apply(lambda x, hh, cc, wih, whh, bih, bhh: _lstm_step(
            x, hh, cc, wih, whh, bih, bhh, self.hidden_size),
            ensure_tensor(inputs), ensure_tensor(h), ensure_tensor(c),
            self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return hn, (hn, cn)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        _cell_params(self, input_size, hidden_size, 3, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        hn = apply(lambda x, h, wih, whh, bih, bhh: _gru_step(
            x, h, wih, whh, bih, bhh, self.hidden_size),
            ensure_tensor(inputs), ensure_tensor(states), self.weight_ih,
            self.weight_hh, self.bias_ih, self.bias_hh)
        return hn, hn

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Wraps a cell into a scan over time (reference `nn/layer/rnn.py:RNN`)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        inputs = ensure_tensor(inputs)
        # eager python loop over time using the cell; for compiled perf use
        # the multi-layer LSTM/GRU/SimpleRNN classes (lax.scan inside).
        axis = 0 if self.time_major else 1
        steps = inputs._value.shape[axis]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        outs = []
        states = initial_states
        from ...tensor.manipulation import stack
        for t in order:
            xt = inputs[t] if self.time_major else inputs[:, t]
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        o_fw, fs = self.rnn_fw(inputs, s_fw)
        o_bw, bs = self.rnn_bw(inputs, s_bw)
        from ...tensor.manipulation import concat
        return concat([o_fw, o_bw], axis=-1), (fs, bs)


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrent net, scan-compiled."""

    MODE = "LSTM"
    N_GATES = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirect else 1
        n_gates = self.N_GATES[self.MODE]
        std = 1.0 / math.sqrt(hidden_size)
        u = Uniform(-std, std)
        self.weights = []
        for layer_i in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer_i == 0 else \
                    hidden_size * self.num_directions
                suffix = f"{layer_i}" + ("_reverse" if d else "")
                wih = self.create_parameter([n_gates * hidden_size, in_sz],
                                            attr=weight_ih_attr,
                                            default_initializer=u)
                whh = self.create_parameter(
                    [n_gates * hidden_size, hidden_size],
                    attr=weight_hh_attr, default_initializer=u)
                bih = self.create_parameter([n_gates * hidden_size],
                                            attr=bias_ih_attr, is_bias=True,
                                            default_initializer=u)
                bhh = self.create_parameter([n_gates * hidden_size],
                                            attr=bias_hh_attr, is_bias=True,
                                            default_initializer=u)
                self.add_parameter(f"weight_ih_l{suffix}", wih)
                self.add_parameter(f"weight_hh_l{suffix}", whh)
                self.add_parameter(f"bias_ih_l{suffix}", bih)
                self.add_parameter(f"bias_hh_l{suffix}", bhh)
                self.weights.append((wih, whh, bih, bhh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        inputs = ensure_tensor(inputs)
        mode = self.MODE
        hs = self.hidden_size
        nl, nd = self.num_layers, self.num_directions
        tm = self.time_major
        act = self.activation
        flat_w = [w for group in self.weights for w in group]

        init_given = initial_states is not None
        init_vals = []
        if init_given:
            if mode == "LSTM":
                h0, c0 = initial_states
                init_vals = [ensure_tensor(h0), ensure_tensor(c0)]
            else:
                init_vals = [ensure_tensor(initial_states)]

        def fn(x, *args):
            from ...amp import maybe_cast_to_compute as _ampc
            # AMP: run the recurrent matmuls in the compute dtype (the
            # cudnn-fp16-LSTM analog); carries then stay bf16 end to end
            ws = [_ampc(w, "matmul") for w in args[:len(flat_w)]]
            inits = args[len(flat_w):]
            if not tm:
                x = jnp.swapaxes(x, 0, 1)  # -> [T, B, F]
            T, B = x.shape[0], x.shape[1]
            if init_given:
                h0_all = inits[0]
                c0_all = inits[1] if mode == "LSTM" else None
            else:
                h0_all = jnp.zeros((nl * nd, B, hs), x.dtype)
                c0_all = jnp.zeros((nl * nd, B, hs), x.dtype) \
                    if mode == "LSTM" else None

            layer_in = x
            last_h, last_c = [], []
            for li in range(nl):
                dir_outs = []
                for d in range(nd):
                    wi = (li * nd + d) * 4
                    wih, whh, bih, bhh = ws[wi:wi + 4]
                    h0 = h0_all[li * nd + d]
                    c0 = c0_all[li * nd + d] if mode == "LSTM" else None
                    seq = jnp.flip(layer_in, 0) if d == 1 else layer_in

                    if mode == "LSTM":
                        def step(carry, xt):
                            h, c = carry
                            hn, cn = _lstm_step(xt, h, c, wih, whh, bih, bhh, hs)
                            return (hn, cn), hn
                        (hT, cT), outs = jax.lax.scan(step, (h0, c0), seq)
                        last_c.append(cT)
                    elif mode == "GRU":
                        def step(carry, xt):
                            hn = _gru_step(xt, carry, wih, whh, bih, bhh, hs)
                            return hn, hn
                        hT, outs = jax.lax.scan(step, h0, seq)
                    else:
                        def step(carry, xt):
                            hn = _simple_step(xt, carry, wih, whh, bih, bhh,
                                              hs, act)
                            return hn, hn
                        hT, outs = jax.lax.scan(step, h0, seq)
                    last_h.append(hT)
                    if d == 1:
                        outs = jnp.flip(outs, 0)
                    dir_outs.append(outs)
                layer_in = jnp.concatenate(dir_outs, axis=-1) if nd == 2 \
                    else dir_outs[0]
            out = layer_in if tm else jnp.swapaxes(layer_in, 0, 1)
            hstack = jnp.stack(last_h, 0)
            if mode == "LSTM":
                return out, hstack, jnp.stack(last_c, 0)
            return out, hstack

        res = apply(fn, inputs, *flat_w, *init_vals)
        if mode == "LSTM":
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        if activation == "relu":
            self.MODE = "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation, **kw)


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"
