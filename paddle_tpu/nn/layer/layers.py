"""nn.Layer — module base class.

Parity target: `python/paddle/fluid/dygraph/layers.py` (reference Layer:
parameter/buffer/sublayer registries, hooks, state_dict, train/eval). The
TPU-relevant difference: parameters are jax.Arrays, and
`paddle_tpu.jit.functional_call` can temporarily bind traced values over the
whole tree so a Layer runs inside a jitted/pjit'd step without rewriting user
code.
"""
import collections

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, Parameter
from ...core.dtype import convert_dtype, get_default_dtype
from ..initializer import Initializer, Constant, XavierUniform

def _unique_name(prefix):
    # routed through paddle.utils.unique_name so `unique_name.guard()`
    # scopes layer/parameter names exactly like the reference
    from ...utils import unique_name as _un
    return _un.generate(prefix)


class ParamAttr:
    """Analog of paddle.ParamAttr (`python/paddle/fluid/param_attr.py`)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"invalid ParamAttr {attr!r}")


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._full_name = _unique_name(
            name_scope or self.__class__.__name__.lower())
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0

    # ---- construction helpers ------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or get_default_dtype()
        # precedence (reference set_global_initializer semantics): an
        # explicit per-param attr wins; otherwise the global override
        # replaces the framework/layer default
        from ..initializer import _global_default
        init = attr.initializer or _global_default(is_bias) or \
            default_initializer or \
            (Constant(0.0) if is_bias else XavierUniform())
        value = init(shape, dtype)
        p = Parameter(value, name=attr.name or _unique_name("param"),
                      trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        p.is_bias = is_bias
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        t = Tensor(jnp.zeros((), convert_dtype(dtype) or get_default_dtype()))
        t.name = name
        return t

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        elif not isinstance(parameter, Parameter):
            raise TypeError("add_parameter requires a Parameter")
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- attribute routing ---------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            if buffers is not None:
                buffers.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter")
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                buffers[name].set_value(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extras = list(self._parameters) + list(self._sub_layers) + \
            list(self._buffers)
        return sorted(set(super().__dir__() + extras))

    # ---- traversal ------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix, True):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True, _seen=None):
        # id-dedup like named_parameters: a sublayer registered under two
        # attribute names (e.g. ErnieModel's `ernie = self.bert` alias)
        # must not emit its buffers twice / under both prefixes
        seen = _seen if _seen is not None else set()
        for name, b in self._buffers.items():
            if b is not None and id(b) not in seen:
                seen.add(id(b))
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix, True, seen)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def children(self):
        for _, layer in self.named_children():
            yield layer

    def named_children(self):
        for name, layer in self._sub_layers.items():
            if layer is not None:
                yield name, layer

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for layer in self.children():
            out.extend(layer.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self.named_children():
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(sub_prefix, True, layers_set)

    def apply(self, fn):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # ---- mode -----------------------------------------------------------
    def train(self):
        self.training = True
        for layer in self.children():
            layer.train()
        return self

    def eval(self):
        self.training = False
        for layer in self.children():
            layer.eval()
        return self

    # ---- dtype/device movement -----------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_all(convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._cast_all(convert_dtype(dtype))
        return self

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    def _cast_all(self, dtype):
        from ...core.dtype import is_floating
        for p in self.parameters():
            if is_floating(p.dtype):
                p._value = p._value.astype(dtype)
        for b in self.buffers():
            if b is not None and is_floating(b.dtype):
                b._value = b._value.astype(dtype)

    # ---- hooks ----------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- call -----------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # ---- state dict -----------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        if destination is None:
            destination = collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            destination[name] = p
        for name, b in self.named_buffers(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            bare = name.rsplit(".", 1)[-1]
            # find owner to check persistability
            destination[name] = b
        # drop non-persistable buffers
        for lname, layer in self.named_sublayers(include_self=True):
            for bname in layer._non_persistable_buffer_names:
                key = f"{lname}.{bname}" if lname else bname
                destination.pop(key, None)
        return destination

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, tgt in own.items():
            if name in state_dict:
                src = state_dict[name]
                val = src._value if isinstance(src, Tensor) else jnp.asarray(src)
                tgt.set_value(val.astype(tgt._value.dtype))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"
