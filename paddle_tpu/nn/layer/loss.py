"""Loss layers. Parity: `python/paddle/nn/layer/loss.py`."""
from .layers import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, self._weight, self._ignore_index,
                          self._reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):  # noqa: A002
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.kl_div(input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):  # noqa: A002
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon = margin, p, epsilon
        self.swap, self.reduction = swap, reduction

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class HSigmoidLoss(Layer):
    """Reference `nn/layer/loss.py` HSigmoidLoss over F.hsigmoid_loss."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self._num_classes = num_classes
        n_nodes = num_classes - 1 if not is_custom else num_classes
        self.weight = self.create_parameter(
            [n_nodes, feature_size], attr=weight_attr)
        self.bias = self.create_parameter([n_nodes, 1], attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):  # noqa: A002
        return F.hsigmoid_loss(input, label, self._num_classes,
                               self.weight, self.bias,
                               path_table=path_table,
                               path_code=path_code)
