"""Activation layers. Parity: `python/paddle/nn/layer/activation.py`."""
from .layers import Layer
from .. import functional as F
from ..initializer import Constant


def _act_layer(name, fn_name=None, **defaults):
    fn = getattr(F, fn_name or name.lower())

    class _Act(Layer):
        def __init__(self, name=None, **kwargs):
            super().__init__()
            self._kwargs = {**defaults, **kwargs}

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU")
ReLU6 = _act_layer("ReLU6")
Sigmoid = _act_layer("Sigmoid")
Tanh = _act_layer("Tanh")
Silu = _act_layer("Silu")
Swish = _act_layer("Swish")
Mish = _act_layer("Mish")
Softsign = _act_layer("Softsign")
Tanhshrink = _act_layer("Tanhshrink")
LogSigmoid = _act_layer("LogSigmoid", "log_sigmoid")
Hardswish = _act_layer("Hardswish")
Hardsigmoid = _act_layer("Hardsigmoid")
Softplus = _act_layer("Softplus")
ThresholdedReLU = _act_layer("ThresholdedReLU", "thresholded_relu")


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self._approximate)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self._scale, self._alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self._scale, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):  # noqa: A002
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, training=self.training)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)
