"""Common layers: Linear, Embedding, Dropout, Flatten, Padding, Upsample.

Parity: `python/paddle/nn/layer/common.py`.
"""
import jax.numpy as jnp

from .layers import Layer, ParamAttr
from ..initializer import XavierUniform, Normal, Constant
from .. import functional as F
from ...core.tensor import Tensor


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b, W:[in,out] — MXU matmul (reference
    `python/paddle/nn/layer/common.py` Linear + `matmul_v2` kernel)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return (f"in_features={self._in_features}, "
                f"out_features={self._out_features}")


class Embedding(Layer):
    """Reference `nn/layer/common.py` Embedding + `lookup_table_v2_op`.

    `sparse=True` (the reference's SelectedRows gradient container) is
    accepted and DISSOLVED by design: on TPU the vjp of a gather is an
    XLA scatter-add into the dense parameter buffer, which beats any
    sparse row container for ICI/HBM (no host-side row bookkeeping, no
    variable shapes). The genuinely-sparse regime — tables too big for
    HBM with few touched rows — is served by
    `distributed.ps.DistributedEmbedding` over the C++ parameter server
    (pull/push of touched rows only), which is the real SelectedRows
    successor here.
    """

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx if padding_idx is None or \
            padding_idx >= 0 else num_embeddings + padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        if self._padding_idx is not None:
            self.weight._value = self.weight._value.at[self._padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__()
        self._pad = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._pad, mode=self._mode, value=self._value,
                     data_format=self._data_format)


class Pad2D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format, name)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format, name)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format, name)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([1, out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        from ...core.tensor import apply
        return apply(lambda a, b: jnp.power(
            jnp.sum(jnp.power(jnp.abs(a - b) + self.epsilon, self.p), axis=-1,
                    keepdims=self.keepdim), 1.0 / self.p), x, y)


class Unfold(Layer):
    def __init__(self, kernel_sizes, dilations=1, paddings=0, strides=1,
                 name=None):
        # param ORDER follows the reference (`nn/layer/common.py` Unfold:
        # kernel_sizes, dilations, paddings, strides) for positional users
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)
