"""Mesh observatory: measured collective latencies vs the planner's
ICI/DCN peaks, a persistent comm DB, and per-step comm attribution.

The communication sibling of the kernel observatory
(telemetry/kernel_obs.py). Every comm number the planner prices today
is analytic: `cost_model.estimate_layout_cost` divides wire bytes by
the static `ICI_BW_BY_CHIP` / `DCN_BW_BYTES` tables and nothing ever
measures them. This module closes that loop:

- **measure_collective / sweep_mesh** — run each mesh collective
  (psum / all_gather / reduce_scatter / all_to_all / ppermute, per mesh
  axis, payloads swept log2 from 256 KiB to 256 MiB) under the
  kernel_obs discipline: AOT ``lower().compile()`` timed separately,
  warmup, then median-of-k ``block_until_ready`` samples against an
  injectable clock.
- **attribution** — place each measurement as an achieved-bandwidth
  fraction against the SAME peak tables the planner prices with
  (`cost_model.ICI_BW_BY_CHIP` / `DCN_BW_BYTES` — one source for
  claims and predictions, like mfu.py's shared FLOPs peaks), with
  wire bytes from `analysis/comm_audit`'s fraction convention so the
  harness and the jaxpr auditor can never disagree about what a
  collective moves. CPU backends have no entry in the peak tables, so
  bw_frac / predicted_ms are None there — no roofline, no drift to
  judge (the kernel_obs exemption rule).
- **CommDB** — tools/comm_db.json: best-known latency per
  (op, axis-size, payload, backend) key, rolled forward only by
  ``commlab --update-db`` with the kernel_db keep-best /
  refuse-non-finite semantics. A measured collective drifting a
  multiplicative band BELOW its DB row fires the `comm_bw_degraded`
  rule (telemetry/health.py); the DB reference rides ON the record
  (db_ms) so in-flight and offline replays judge identically.
- per-step attribution lands through TelemetryRecorder: wall-time
  ``collective.*`` spans aggregate into the step record's ``comm_ms``
  / ``comm_frac`` fields (spans tagged ``traced=True`` by
  distributed/collective.py cover trace time and are excluded), and
  per-rank step-boundary skew feeds the `straggler` rule.

Opt-in flag: set ``PADDLE_TPU_COMM_DB=/path/to/comm_db.json`` (or
``=1`` for the checked-in tools/comm_db.json) to let measurements
attach their DB reference (db_ms) for the drift rule. Unset (the
default), measurements carry no reference and the rule has no
jurisdiction — CI smoke sweeps on arbitrary hosts stay quiet.

Every measurement is emitted as a typed ``kind=commbench`` record
(telemetry/sink.make_commbench_record, cross-checked by
tools/trace_check.py) and mirrored as ``comm.*`` gauges on /metrics.
CLI: tools/commlab.py (--smoke / --selfcheck / --update-db).
"""
import functools
import json
import math
import os
import statistics
import time

import numpy as np

from .. import monitor
from .sink import make_commbench_record

__all__ = [
    "CommDB", "CommMeasureResult", "DEFAULT_DB_PATH", "PAYLOAD_MAX_BYTES",
    "PAYLOAD_MIN_BYTES", "SWEEP_OPS", "attribution", "db_flag_path",
    "db_key", "device_peak_ici_bw", "measure_collective", "payload_sweep",
    "rank_step_skew", "sweep_axes", "sweep_mesh", "sweep_program",
    "wire_bytes",
]

# the sweep matrix: every shard_map collective the training stack issues
# (distributed/collective.py primitives; pmean/pmax lower to psum)
SWEEP_OPS = ("psum", "all_gather", "reduce_scatter", "all_to_all",
             "ppermute")

# log2 payload sweep bounds — 256 KiB (latency-dominated) to 256 MiB
# (bandwidth-saturated); commlab --smoke scales these down for the
# 8-virtual-device CPU mesh, where a MiB-scale sweep buys nothing
PAYLOAD_MIN_BYTES = 256 * 1024
PAYLOAD_MAX_BYTES = 256 * 1024 * 1024

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_DB_PATH = os.path.join(_REPO, "tools", "comm_db.json")

DB_SCHEMA = 1
ENV_FLAG = "PADDLE_TPU_COMM_DB"

# second dim of every swept operand: one full lane register, so payload
# rounding only ever moves along the first (sharded) dim
_SWEEP_COLS = 128


def payload_sweep(min_bytes=PAYLOAD_MIN_BYTES, max_bytes=PAYLOAD_MAX_BYTES):
    """The log2 payload ladder [min, 2*min, ..., <= max], in bytes."""
    out = []
    b = int(min_bytes)
    while b <= int(max_bytes):
        out.append(b)
        b *= 2
    return out


def db_key(op, axis_size, payload_bytes, backend):
    """``op|ax<n>|<payload_bytes>|<backend>`` — the DB's primary key:
    the (op, axis-size, payload, backend) identity of one measurement,
    mirroring kernel_obs.db_key's kernel|sig|dtype|backend."""
    return f"{op}|ax{int(axis_size)}|{int(payload_bytes)}|{backend}"


# ---------------------------------------------------------------------------
# peaks + attribution (the planner's own tables — one source of truth)
# ---------------------------------------------------------------------------

def device_peak_ici_bw(kind=None):
    """Aggregate per-chip ICI bandwidth (bytes/s) for a device-kind
    string, from the SAME `cost_model.ICI_BW_BY_CHIP` table the planner
    prices layouts with (plus the 'v5 lite'/'v6 lite' device_kind
    aliases mfu.py's tables use). None when unknown (CPU backends) —
    the bandwidth fraction is then not computable and the drift rules
    have no jurisdiction."""
    from ..cost_model import ICI_BW_BY_CHIP
    from .mfu import _match_kind
    table = dict(ICI_BW_BY_CHIP)
    table.setdefault("v5 lite", ICI_BW_BY_CHIP["v5e"])
    table.setdefault("v6 lite", ICI_BW_BY_CHIP["v6e"])
    return _match_kind(table, kind)


def wire_bytes(op, payload_bytes, axis_size):
    """Wire traffic per participant for `op` moving a `payload_bytes`
    operand over an axis of `axis_size` — delegating to
    `analysis/comm_audit`'s fraction convention (all_gather /
    reduce_scatter / all_to_all (n-1)/n, psum 2(n-1)/n ring all-reduce,
    ppermute full operand) so the measurement harness and the jaxpr
    auditor share ONE rule and the third honesty leg is a real check,
    not a tautology over two copies of the same table."""
    from ..analysis.comm_audit import _wire_bytes
    return float(_wire_bytes(op, float(payload_bytes), int(axis_size)))


def attribution(op, payload_bytes, axis_size, time_ms, peak_bw=None,
                device_kind=None, over_dcn=False):
    """Attribute one measured collective against the planner's peaks:

    - wire_bytes — comm_audit-convention wire traffic of the operand;
    - achieved_bw — wire_bytes / measured seconds (None without a
      positive time);
    - bw_frac — achieved over peak, clamped to [0, 1] (None on CPU
      backends, where `device_peak_ici_bw` answers None);
    - predicted_ms — wire_bytes / peak * 1e3, the analytic floor
      `calibration_from_comm_records` ratios measured time against;
    - medium — 'dcn' when over_dcn, 'ici' when an ICI peak resolved,
      None otherwise (CPU).
    """
    from ..cost_model import DCN_BW_BYTES
    wb = wire_bytes(op, payload_bytes, axis_size)
    if peak_bw is None:
        peak_bw = float(DCN_BW_BYTES) if over_dcn \
            else device_peak_ici_bw(device_kind)
    t_s = time_ms / 1e3 if time_ms and time_ms > 0 else None
    out = {"wire_bytes": wb, "achieved_bw": None, "bw_frac": None,
           "predicted_ms": None, "peak_bw": peak_bw,
           "medium": ("dcn" if over_dcn
                      else ("ici" if peak_bw else None))}
    if t_s and wb:
        out["achieved_bw"] = wb / t_s
        if peak_bw:
            out["bw_frac"] = min(1.0, out["achieved_bw"] / peak_bw)
    if peak_bw and wb:
        out["predicted_ms"] = wb / peak_bw * 1e3
    return out


# ---------------------------------------------------------------------------
# the sweep programs
# ---------------------------------------------------------------------------

def _shard_map(fn, mesh, in_specs, out_specs):
    from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def sweep_program(op, axis, mesh, payload_bytes, dtype=np.float32):
    """Build one swept collective as a global-view callable.

    Returns (fn, global_sds, in_spec, actual_payload_bytes): `fn` takes
    ONE global array of `global_sds`'s shape placed with
    NamedSharding(mesh, in_spec); inside, shard_map runs `op` over
    `axis`. Shapes are chosen so the PER-DEVICE operand is
    `actual_payload_bytes` (payload rounded to the lane/divisibility
    grid) — exactly the per-device accounting
    `analysis/comm_audit.collective_wire_bytes` applies to shard_map
    bodies, which is what makes the third honesty leg's comparison
    meaningful."""
    import jax
    from jax.sharding import PartitionSpec as P

    if op not in SWEEP_OPS:
        raise ValueError(f"unknown sweep op {op!r} "
                         f"(expected one of {SWEEP_OPS})")
    n = int(mesh.shape[axis])
    itemsize = np.dtype(dtype).itemsize
    rows = max(1, int(payload_bytes) // (_SWEEP_COLS * itemsize))
    if op == "all_to_all":
        # per-device rows must split evenly over the axis
        rows = max(n, rows // n * n)
    if op == "reduce_scatter":
        # operand is the FULL (replicated) array; output rows must
        # divide over the axis
        rows = max(n, rows // n * n)
        global_shape = (rows, _SWEEP_COLS)
        in_spec, out_spec = P(), P(axis)
        body = lambda v: jax.lax.psum_scatter(   # noqa: E731
            v, axis, scatter_dimension=0, tiled=True)
    else:
        global_shape = (n * rows, _SWEEP_COLS)
        in_spec = P(axis)
        if op == "psum":
            out_spec = P()
            body = lambda v: jax.lax.psum(v, axis)           # noqa: E731
        elif op == "all_gather":
            out_spec = P()
            body = lambda v: jax.lax.all_gather(             # noqa: E731
                v, axis, axis=0, tiled=True)
        elif op == "ppermute":
            out_spec = P(axis)
            perm = [(i, (i + 1) % n) for i in range(n)]
            body = lambda v: jax.lax.ppermute(v, axis, perm)  # noqa: E731
        else:   # all_to_all
            out_spec = P(axis)
            body = lambda v: jax.lax.all_to_all(             # noqa: E731
                v, axis, split_axis=0, concat_axis=0, tiled=True)
    fn = _shard_map(body, mesh, in_spec, out_spec)
    sds = jax.ShapeDtypeStruct(global_shape, np.dtype(dtype))
    actual = rows * _SWEEP_COLS * itemsize
    return fn, sds, in_spec, actual


# ---------------------------------------------------------------------------
# measurement harness (the kernel_obs timing discipline)
# ---------------------------------------------------------------------------

class CommMeasureResult:
    """One measured (op, axis, payload) point, bandwidth-attributed."""

    __slots__ = ("op", "axis", "axis_size", "payload_bytes", "backend",
                 "time_ms", "compile_ms", "wire_bytes", "achieved_bw",
                 "bw_frac", "predicted_ms", "peak_bw", "medium",
                 "n_samples", "warmup", "db_ms")

    def __init__(self, **kw):
        for s in self.__slots__:
            setattr(self, s, kw.get(s))

    def key(self):
        return db_key(self.op, self.axis_size, self.payload_bytes,
                      self.backend)

    def to_record(self, rank=0, event="measure"):
        return make_commbench_record(
            op=self.op, axis=self.axis, axis_size=self.axis_size,
            payload_bytes=self.payload_bytes, backend=self.backend,
            time_ms=self.time_ms, rank=rank, compile_ms=self.compile_ms,
            wire_bytes=self.wire_bytes, achieved_bw=self.achieved_bw,
            peak_bw=self.peak_bw, bw_frac=self.bw_frac,
            predicted_ms=self.predicted_ms, medium=self.medium,
            db_key=self.key(), db_ms=self.db_ms,
            n_samples=self.n_samples, warmup=self.warmup, event=event)


def _timed_call(fn, arr, warmup, k, clock):
    """AOT-compile `fn` over `arr`, then warmup + k timed
    ``block_until_ready`` iterations; returns
    (median_ms, compile_ms, samples). compile_ms is measured around
    lower().compile() — the compile-observatory discipline — so it can
    never leak into the execute median."""
    import jax

    t0 = clock()
    compiled = jax.jit(fn).lower(arr).compile()
    compile_ms = (clock() - t0) * 1e3
    for _ in range(max(0, warmup)):
        jax.block_until_ready(compiled(arr))
    samples = []
    for _ in range(max(1, k)):
        t0 = clock()
        jax.block_until_ready(compiled(arr))
        samples.append((clock() - t0) * 1e3)
    return statistics.median(samples), compile_ms, samples


def measure_collective(op, axis, mesh=None, payload_bytes=PAYLOAD_MIN_BYTES,
                       dtype=np.float32, warmup=2, k=5, clock=None,
                       over_dcn=False, db=None):
    """Measure one (op, axis, payload) point on the live mesh:
    median-of-k wall time of the AOT-compiled collective, attributed
    against the planner's peak tables. Deterministic given `clock`
    (tests inject a fake counter). When the PADDLE_TPU_COMM_DB flag is
    set (or `db` is passed), the best-known DB latency for this key is
    attached as `db_ms` — the reference the `comm_bw_degraded` rule
    judges against."""
    import jax
    from jax.sharding import NamedSharding

    from ..distributed import env

    clock = clock or time.perf_counter
    mesh = mesh if mesh is not None else env.current_mesh()
    if mesh is None:
        raise RuntimeError("measure_collective: no mesh — pass mesh= or "
                           "env.build_mesh(...) first")
    fn, sds, in_spec, actual = sweep_program(op, axis, mesh,
                                             payload_bytes, dtype)
    host = np.arange(int(np.prod(sds.shape)),
                     dtype=np.dtype(dtype)).reshape(sds.shape)
    arr = jax.device_put(host, NamedSharding(mesh, in_spec))
    time_ms, compile_ms, _ = _timed_call(fn, arr, warmup, k, clock)
    backend = jax.default_backend()
    n = int(mesh.shape[axis])
    attr = attribution(op, actual, n, time_ms, over_dcn=over_dcn)
    db_ms = None
    ref = db if db is not None else _flagged_db()
    if ref is not None:
        db_ms = ref.best_ms(op, n, actual, backend)
    res = CommMeasureResult(
        op=op, axis=str(axis), axis_size=n, payload_bytes=actual,
        backend=backend, time_ms=time_ms, compile_ms=compile_ms,
        wire_bytes=attr["wire_bytes"], achieved_bw=attr["achieved_bw"],
        bw_frac=attr["bw_frac"], predicted_ms=attr["predicted_ms"],
        peak_bw=attr["peak_bw"], medium=attr["medium"],
        n_samples=max(1, k), warmup=max(0, warmup), db_ms=db_ms)
    _export_gauges(res)
    return res


def _export_gauges(res):
    """Mirror one measurement onto /metrics (telemetry.metrics_http
    scrapes monitor.snapshot_typed verbatim)."""
    monitor.set_gauge(f"comm.{res.op}.ms", float(res.time_ms))
    if res.achieved_bw is not None:
        monitor.set_gauge(f"comm.{res.op}.achieved_bw",
                          float(res.achieved_bw))
    if res.bw_frac is not None:
        monitor.set_gauge(f"comm.{res.op}.bw_frac", float(res.bw_frac))
    monitor.incr("comm.measured")


def sweep_axes(mesh):
    """The mesh axes worth sweeping: size > 1 (a 1-axis collective
    moves nothing), in mesh axis order."""
    return [a for a in mesh.axis_names if int(mesh.shape[a]) > 1]


def sweep_mesh(mesh=None, ops=SWEEP_OPS, payloads=None, dtype=np.float32,
               warmup=2, k=5, clock=None, over_dcn_axes=(), db=None):
    """The full sweep: every op x every size>1 mesh axis x every
    payload rung. Returns [CommMeasureResult, ...] in deterministic
    (op, axis, payload) order. `over_dcn_axes` marks axes priced
    against DCN (the outer axis of a two-level plan)."""
    from ..distributed import env

    mesh = mesh if mesh is not None else env.current_mesh()
    if mesh is None:
        raise RuntimeError("sweep_mesh: no mesh — pass mesh= or "
                           "env.build_mesh(...) first")
    payloads = list(payloads) if payloads is not None else payload_sweep()
    out = []
    for op in ops:
        for axis in sweep_axes(mesh):
            for payload in payloads:
                out.append(measure_collective(
                    op, axis, mesh=mesh, payload_bytes=payload,
                    dtype=dtype, warmup=warmup, k=k, clock=clock,
                    over_dcn=axis in over_dcn_axes, db=db))
    return out


# ---------------------------------------------------------------------------
# per-rank step-boundary skew (the straggler measurement)
# ---------------------------------------------------------------------------

def rank_step_skew(records):
    """Per-step, per-rank step-boundary skew over kind=step records
    from MULTIPLE ranks: for each step index seen on >= 2 ranks,
    skew_ms[rank] = that rank's step_ms minus the fastest rank's. The
    offline view of what the `straggler` rule (telemetry/health.py)
    judges in flight — a rank persistently above the band is holding
    every collective barrier open for the whole mesh. Returns
    {step: {rank: skew_ms}}, only steps with >= 2 ranks."""
    by_step = {}
    for rec in records or ():
        if not isinstance(rec, dict) or rec.get("kind", "step") != "step":
            continue
        step, rank, ms = rec.get("step"), rec.get("rank"), rec.get("step_ms")
        if step is None or rank is None \
                or not isinstance(ms, (int, float)) or not math.isfinite(ms):
            continue
        by_step.setdefault(int(step), {})[int(rank)] = float(ms)
    out = {}
    for step, ranks in sorted(by_step.items()):
        if len(ranks) < 2:
            continue
        fastest = min(ranks.values())
        out[step] = {r: round(ms - fastest, 4)
                     for r, ms in sorted(ranks.items())}
    return out


# ---------------------------------------------------------------------------
# persistent measurement DB (the kernel_db contract)
# ---------------------------------------------------------------------------

def _finite(v):
    return isinstance(v, (int, float)) and math.isfinite(v)


class CommDB:
    """tools/comm_db.json: best-known latency per (op, axis-size,
    payload, backend) key. `update` REFUSES non-finite rows (the
    bench_gate --update-baseline contract) and with keep_best skips
    rows slower than the incumbent — losing a race is not an error."""

    def __init__(self, path=DEFAULT_DB_PATH):
        self.path = path
        self.entries = {}
        self.comment = ""
        if path and os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
            self.entries = dict(data.get("entries", {}))
            self.comment = data.get("comment", "")

    def lookup(self, op, axis_size=None, payload_bytes=None, backend=None):
        """Entries for one op, narrowed by whatever axes the caller
        knows. Returns [(key, entry), ...]."""
        out = []
        for key, e in self.entries.items():
            if e.get("op") != op:
                continue
            if axis_size is not None and e.get("axis_size") != int(axis_size):
                continue
            if payload_bytes is not None \
                    and e.get("payload_bytes") != int(payload_bytes):
                continue
            if backend is not None and e.get("backend") != backend:
                continue
            out.append((key, e))
        return out

    def best_ms(self, op, axis_size, payload_bytes, backend):
        e = self.entries.get(db_key(op, axis_size, payload_bytes, backend))
        return e.get("best_ms") if e else None

    def update(self, results, keep_best=True):
        """Roll measured rows in. `results` is [CommMeasureResult] or
        [(key, entry_dict)]. Returns (updated_keys, refused) where
        refused is [(key, reason)] — non-finite timings never land."""
        updated, refused = [], []
        for item in results:
            if isinstance(item, CommMeasureResult):
                key = item.key()
                entry = {
                    "op": item.op, "axis_size": int(item.axis_size),
                    "payload_bytes": int(item.payload_bytes),
                    "backend": item.backend, "best_ms": item.time_ms,
                    "wire_bytes": item.wire_bytes,
                    "predicted_ms": item.predicted_ms,
                }
            else:
                key, entry = item
                entry = dict(entry)
                # the key IS the identity — backfill the lookup axes
                # from it so a hand-built (key, entry) pair can't ship
                # an entry lookup() would never find
                parts = key.split("|")
                if len(parts) == 4 and parts[1].startswith("ax"):
                    entry.setdefault("op", parts[0])
                    try:
                        entry.setdefault("axis_size", int(parts[1][2:]))
                        entry.setdefault("payload_bytes", int(parts[2]))
                    except ValueError:
                        pass
                    entry.setdefault("backend", parts[3])
            ms = entry.get("best_ms")
            if not _finite(ms) or ms < 0:
                refused.append(
                    (key, f"REFUSED: non-finite best_ms {ms!r}"))
                continue
            bad = [k for k, v in entry.items()
                   if isinstance(v, float) and not math.isfinite(v)]
            if bad:
                refused.append(
                    (key, f"REFUSED: non-finite value(s) in {bad}"))
                continue
            old = self.entries.get(key)
            if keep_best and old and _finite(old.get("best_ms")) \
                    and old["best_ms"] <= ms:
                continue
            self.entries[key] = entry
            updated.append(key)
        return updated, refused

    def save(self, path=None):
        path = path or self.path
        data = {"schema": DB_SCHEMA, "comment": self.comment,
                "entries": {k: self.entries[k]
                            for k in sorted(self.entries)}}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# opt-in DB reference resolution (the kernel_obs flag pattern)
# ---------------------------------------------------------------------------

def db_flag_path():
    """The opt-in flag: PADDLE_TPU_COMM_DB unset/empty/'0' -> None (no
    DB reference attached, the drift rule has no jurisdiction); '1' ->
    the checked-in tools/comm_db.json; anything else -> that path."""
    raw = os.environ.get(ENV_FLAG, "").strip()
    if not raw or raw == "0":
        return None
    return DEFAULT_DB_PATH if raw == "1" else raw


@functools.lru_cache(maxsize=8)
def _load_db(path):
    try:
        return CommDB(path)
    except Exception:
        return None


def clear_db_cache():
    _load_db.cache_clear()


def _flagged_db():
    path = db_flag_path()
    return _load_db(path) if path else None
