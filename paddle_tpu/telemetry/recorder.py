"""Training flight recorder: per-step telemetry with compile/execute split.

The reference stack spreads this over RecordEvent/DeviceTracer
(`platform/profiler.h`), the Monitor StatRegistry (`platform/monitor.h`)
and ad-hoc trainer logging; here one recorder unifies them for the
TPU-native regime, where the interesting split is *XLA compile time vs.
execute time*, not per-op kernels (XLA owns those — `jax.profiler`'s
XPlane trace covers device detail).

Mechanics:

- compile time is observed through `jax.monitoring`'s event-duration
  stream (jaxpr trace + MLIR lowering + backend_compile — the same
  events `jax.stages` lowering/compilation emit), accumulated into
  whichever step window is open. Step 0 of a jitted loop therefore shows
  nonzero compile_ms; steady-state steps show 0.0 and advance the
  compile-cache hit counter.
- spans (`telemetry.span("name")`) are host intervals tagged with the
  recorder's rank; `distributed/collective.py` tags each eager
  collective, so per-step comm time is attributable. Spans export to a
  multi-rank Chrome trace (sink.export_chrome_tracing).
- every closed step writes one JSONL record (sink.make_step_record):
  step, loss, step_ms, compile_ms, execute_ms, tokens/sec, MFU,
  mem_bytes, per-collective ms, cache hit/miss counters.
- `paddle_tpu.monitor` counters (`telemetry.steps`,
  `telemetry.compile_cache_hits/misses`) advance with every step so a
  stuck job is still triagable from `monitor.snapshot()` alone.
"""
import contextlib
import threading
import time

import jax

from .. import monitor
from . import mfu as _mfu
from .sink import JsonlSink, make_step_record

_LOCK = threading.Lock()
_RECORDER_STACK = []          # guarded by: _LOCK — active (context-entered) recorders
_OPEN_STEPS = []              # guarded by: _LOCK — open _StepWindow objects (compile sink)
_OPEN_SPANS = []              # guarded by: _LOCK — spans entered but not yet exited (any thread)
_LISTENER_INSTALLED = False   # guarded by: none (idempotent install; main-thread hook)

# jax.monitoring events that constitute "compile" for the split; all
# three fire on a jit cache miss and none on a hit
_COMPILE_EVENTS = (
    "/jax/core/compile/jaxpr_trace_duration",
    "/jax/core/compile/jaxpr_to_mlir_module_duration",
    "/jax/core/compile/backend_compile_duration",
)


def _compile_listener(event, duration, **kwargs):
    if event not in _COMPILE_EVENTS:
        return
    with _LOCK:
        for win in _OPEN_STEPS:
            win.compile_secs += duration


def _install_listener():
    """Idempotently hook jax's compile-event stream. The listener stays
    registered for the process lifetime (it is a no-op with no open step
    windows — a dict lookup and a lock-free len check)."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    jax.monitoring.register_event_duration_secs_listener(_compile_listener)
    _LISTENER_INSTALLED = True


def current_recorder():
    """The innermost context-active TelemetryRecorder, or None."""
    with _LOCK:
        return _RECORDER_STACK[-1] if _RECORDER_STACK else None


class _StepWindow:
    """One open step measurement: wall clock + compile accumulation +
    span capture start index."""

    def __init__(self, recorder):
        self.recorder = recorder
        self.compile_secs = 0.0
        self.loss = None
        self.extra = {}
        self.span_start = len(recorder.spans)
        self.t0 = time.perf_counter()

    def note(self, loss=None, **extra):
        """Attach the step's loss (Tensor/array/float — fetched lazily at
        close, which also syncs the device) and any extra record fields."""
        if loss is not None:
            self.loss = loss
        self.extra.update(extra)
        return self


@contextlib.contextmanager
def auto_step(**extra):
    """Bracket a train-step body with the active recorder, if any.

    Used by TrainStep/ShardedTrainStep so any step executed while a
    recorder is context-active gets recorded with zero call-site changes.
    Re-entrant calls (a recorder-managed wrapper around an instrumented
    step) record only the OUTERMOST window. Yields a _StepWindow (or an
    inert one when no recorder is active) whose .note(loss=...) feeds the
    record.
    """
    rec = current_recorder()
    if rec is None or rec._open:
        yield _InertWindow()
        return
    win = rec.start_step()
    if extra:
        win.extra.update(extra)
    try:
        yield win
    finally:
        rec.end_step()


class _InertWindow:
    def note(self, loss=None, **extra):
        return self


def _push_open_span(name, cat, t0, rec=None, rank=None, attrs=None):
    """Register a just-entered span in the module-wide open-span table.
    The hang watchdog reads this table to NAME what a stalled step is
    stuck inside (e.g. `collective.all_reduce`), and chrome export
    closes these instead of dropping them. Returns the entry (identity
    is the removal token)."""
    entry = {"name": name, "cat": cat, "t0": t0,
             "tid": threading.get_ident(),
             "thread": threading.current_thread().name,
             "rec": rec, "rank": rank, "attrs": dict(attrs or {})}
    with _LOCK:
        _OPEN_SPANS.append(entry)
    return entry


def _pop_open_span(entry):
    with _LOCK:
        try:
            _OPEN_SPANS.remove(entry)
        except ValueError:
            pass


def open_spans():
    """Snapshot of every currently-open telemetry span (all threads):
    [{name, cat, age_s, thread, rank, attrs}], oldest first. This is
    what the watchdog black-box dump records, so a hang inside an
    instrumented region is attributable without a debugger."""
    now = time.perf_counter()
    with _LOCK:
        entries = list(_OPEN_SPANS)
    return [{"name": e["name"], "cat": e["cat"],
             "age_s": round(now - e["t0"], 4), "thread": e["thread"],
             "rank": e["rank"], "attrs": e["attrs"]} for e in entries]


@contextlib.contextmanager
def span(name, cat="host", rank=None, **attrs):
    """Record a named host span into the active recorder (and bridge it
    into paddle_tpu.profiler's table when that profiler is enabled, so
    existing RecordEvent consumers keep seeing one merged view). Extra
    keyword attrs (e.g. axis/shape on collectives) ride into the span
    dict, the chrome-trace `args`, and the watchdog's open-span dump.
    While the body runs the span sits in the open-span table, so a hang
    inside it is named in black-box dumps."""
    rec = current_recorder()
    from .. import profiler as _profiler
    ev = _profiler.RecordEvent(name) if _profiler._GLOBAL["enabled"] else None
    t0 = time.perf_counter()
    if ev is not None:
        ev._t0 = t0
        ev._from_telemetry = True   # span() owns recorder routing here
    entry = _push_open_span(name, cat, t0, rec=rec,
                            rank=rank if rank is not None
                            else (rec.rank if rec is not None else None),
                            attrs=attrs)
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        _pop_open_span(entry)
        if ev is not None:
            ev.end()
        if rec is not None:
            rec.add_span(name, t0, dur, cat=cat, rank=rank, args=attrs)


class StepTimer:
    """Explicit compile/execute split for a plain jittable function via
    `jax.stages`: an AOT cache keyed on input avals. A key miss runs
    lower()+compile() under the clock (compile_ms); a hit dispatches the
    cached executable (execute only). The deterministic-counter
    counterpart to the listener-based split in TelemetryRecorder.

    timer = StepTimer(fn); out = timer(*args)
    timer.cache_hits / timer.cache_misses / timer.last_compile_ms
    """

    def __init__(self, fn, recorder=None):
        self._fn = fn
        self._cache = {}
        self._last_compiled = None
        self.recorder = recorder
        self.cache_hits = 0
        self.cache_misses = 0
        self.last_compile_ms = 0.0
        self.last_execute_ms = 0.0

    @staticmethod
    def _key(args):
        leaves = jax.tree_util.tree_leaves(args)
        return tuple(
            (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", type(x))))
            for x in leaves)

    def __call__(self, *args):
        from . import compile_obs
        key = self._key(args)
        compiled = self._cache.get(key)
        obs = compile_obs.current_observatory()
        if compiled is None:
            with (obs.compiling() if obs is not None
                  else contextlib.nullcontext()):
                t0 = time.perf_counter()
                compiled = jax.jit(self._fn).lower(*args).compile()
                self.last_compile_ms = (time.perf_counter() - t0) * 1000.0
            self._cache[key] = compiled
            self._last_compiled = compiled
            self.cache_misses += 1
            monitor.incr("telemetry.aot_cache_misses")
            if obs is not None:
                # attribute this compile to the observatory's ledger
                # (cause diffs, memory/cost, storm rule) instead of the
                # unattributed jax-event stream; the timer's own call
                # count is the step clock for its records
                obs.observe(
                    f"StepTimer:{getattr(self._fn, '__name__', 'fn')}",
                    compile_obs.signature_of(args), self.last_compile_ms,
                    compiled=compiled,
                    step=self.cache_hits + self.cache_misses - 1)
        else:
            self.last_compile_ms = 0.0
            self.cache_hits += 1
            monitor.incr("telemetry.aot_cache_hits")
        t0 = time.perf_counter()
        out = compiled(*args)
        jax.block_until_ready(out)
        self.last_execute_ms = (time.perf_counter() - t0) * 1000.0
        if self.recorder is not None:
            extra = {}
            mem = self.memory_analysis_dict()
            if mem is not None:
                # last-compiled HBM breakdown rides the step record, so
                # AOT-cache behaviour is visible in the JSONL, not just
                # in in-process counters
                extra["hbm"] = mem
            self.recorder.record_external_step(
                step_ms=self.last_compile_ms + self.last_execute_ms,
                compile_ms=self.last_compile_ms,
                cache_hits=self.cache_hits,
                cache_misses=self.cache_misses, **extra)
        return out

    def memory_analysis(self):
        """Compiled memory analysis of the last-compiled executable (HBM
        argument/output/temp bytes), None when unavailable."""
        if self._last_compiled is None:
            return None
        try:
            return self._last_compiled.memory_analysis()
        except Exception:
            return None

    def memory_analysis_dict(self):
        """Same, flattened to plain byte counts (the form the step
        record and compile observatory carry), None when unavailable."""
        if self._last_compiled is None:
            return None
        from .compile_obs import memory_analysis_dict
        return memory_analysis_dict(self._last_compiled)


class TelemetryRecorder:
    """Flight recorder for a training loop.

    rec = TelemetryRecorder(sink="run.jsonl", tokens_per_step=B*S,
                            flops_per_token=mfu.model_flops_per_token(...))
    with rec:                      # recorder active: TrainStep auto-records
        for batch in loader:
            loss = train_step(*batch)

    or wrap an arbitrary step callable:  step = rec.wrap(train_step).

    Per closed step, one schema record (sink.make_step_record) goes to the
    JSONL sink and to `rec.records`. MFU inputs: flops_per_step (exact,
    e.g. mfu.train_step_flops) OR flops_per_token (analytic) combined with
    tokens_per_step; peak_flops defaults from the device kind
    (mfu.device_peak_flops — None on CPU => MFU 0.0, still finite).
    """

    def __init__(self, sink=None, rank=0, tokens_per_step=None,
                 flops_per_step=None, flops_per_token=None,
                 peak_flops=None, n_devices=None, track_memory=True):
        self._owns_sink = isinstance(sink, str)
        self.sink = JsonlSink(sink) if self._owns_sink else sink
        self.rank = int(rank)
        self.tokens_per_step = tokens_per_step
        self.flops_per_step = flops_per_step
        self.flops_per_token = flops_per_token
        if peak_flops is None:
            peak_flops = _mfu.device_peak_flops()
        self.peak_flops = peak_flops
        self.n_devices = n_devices or 1
        self.track_memory = track_memory
        self.records = []
        self.spans = []
        self.cache_hits = 0
        self.cache_misses = 0
        self._step_idx = 0
        self._win = None
        _install_listener()

    # -- span API ----------------------------------------------------------
    def add_span(self, name, t0, dur, cat="host", rank=None, tid=None,
                 args=None):
        sp = {
            "name": name, "t0": float(t0), "dur": float(dur),
            "cat": cat, "rank": self.rank if rank is None else int(rank),
            "tid": threading.get_ident() % 1000 if tid is None else tid}
        if args:
            sp["args"] = {k: (v if isinstance(v, (int, float, str, bool))
                              else repr(v)) for k, v in args.items()}
        self.spans.append(sp)

    def open_span_dicts(self):
        """Spans currently open under this recorder, synthesized as
        closed span dicts ending 'now' and tagged args={'open': True} —
        chrome export includes them instead of dropping them."""
        now = time.perf_counter()
        with _LOCK:
            entries = [e for e in _OPEN_SPANS if e["rec"] is self]
        return [{"name": e["name"], "t0": float(e["t0"]),
                 "dur": float(now - e["t0"]), "cat": e["cat"],
                 "rank": self.rank if e["rank"] is None else e["rank"],
                 "tid": e["tid"] % 1000,
                 "args": {"open": True, **{k: repr(v) for k, v
                                           in e["attrs"].items()}}}
                for e in entries]

    # -- step lifecycle ----------------------------------------------------
    @property
    def _open(self):
        return self._win is not None

    def start_step(self):
        if self._win is not None:
            raise RuntimeError("TelemetryRecorder: step already open")
        self._win = _StepWindow(self)
        with _LOCK:
            _OPEN_STEPS.append(self._win)
        return self._win

    def end_step(self, loss=None, **extra):
        win = self._win
        if win is None:
            raise RuntimeError("TelemetryRecorder: no open step")
        if loss is not None:
            win.loss = loss
        win.extra.update(extra)
        loss_val = None
        if win.loss is not None:
            # fetching the scalar double-duties as the device sync, so
            # step_ms covers the full computation, not just dispatch
            try:
                v = win.loss
                v = v.item() if hasattr(v, "item") else v
                loss_val = float(v)
            except Exception:
                loss_val = None
        t1 = time.perf_counter()
        with _LOCK:
            _OPEN_STEPS.remove(win)
        self._win = None
        step_s = t1 - win.t0
        compile_ms = win.compile_secs * 1000.0
        if compile_ms > 0:
            self.cache_misses += 1
            monitor.incr("telemetry.compile_cache_misses")
        else:
            self.cache_hits += 1
            monitor.incr("telemetry.compile_cache_hits")
        monitor.incr("telemetry.steps")

        execute_s = max(1e-9, step_s - win.compile_secs)
        tokens_per_sec = None
        if self.tokens_per_step:
            tokens_per_sec = self.tokens_per_step / execute_s
        flops_per_step = self.flops_per_step
        if flops_per_step is None and self.flops_per_token \
                and self.tokens_per_step:
            flops_per_step = self.flops_per_token * self.tokens_per_step
        mfu_val = None
        if flops_per_step is not None:
            mfu_val = _mfu.mfu(flops_per_step, execute_s,
                               peak_flops=self.peak_flops,
                               n_devices=self.n_devices)
        mem_bytes = self._live_bytes() if self.track_memory else None
        coll, comm_ms = self._collect_collectives(win.span_start)
        # compute-vs-communication decomposition: the wall-time
        # collective total and its bounded share of the step
        # (telemetry/comm_obs — validated by sink + trace_check)
        step_ms_total = step_s * 1000.0
        comm_frac = min(1.0, comm_ms / step_ms_total) \
            if step_ms_total > 0 else 0.0

        # an external step source (StepTimer) reports its OWN AOT cache
        # counters; they override the recorder's listener-derived ones
        extra = dict(win.extra)
        cache_hits = extra.pop("cache_hits", self.cache_hits)
        cache_misses = extra.pop("cache_misses", self.cache_misses)
        # input-pipeline taps (io.prefetch): the loader stashed the
        # fetch-wait stats of the batch this step consumed; pop them
        # one-shot so they land in THIS step's record only
        try:
            from ..io.prefetch import consume_step_input_stats
            istats = consume_step_input_stats()
        except Exception:
            istats = None
        if istats:
            for k, v in istats.items():
                extra.setdefault(k, v)
        rec = make_step_record(
            step=self._step_idx, step_ms=step_s * 1000.0,
            compile_ms=compile_ms, rank=self.rank, loss=loss_val,
            tokens_per_sec=tokens_per_sec, mfu=mfu_val, mem_bytes=mem_bytes,
            cache_hits=cache_hits, cache_misses=cache_misses,
            collectives=coll,
            comm_ms=comm_ms if coll else None,
            comm_frac=comm_frac if coll else None, **extra)
        # the whole step is also a span, so the JSONL ledger and the
        # chrome trace describe the same intervals
        self.add_span(f"step {self._step_idx}", win.t0, step_s, cat="step")
        self._step_idx += 1
        self.records.append(rec)
        if self.sink is not None:
            self.sink.write(rec)
        return rec

    def record_external_step(self, step_ms, compile_ms, **kwargs):
        """Record a step measured elsewhere (StepTimer, bench phases)."""
        win = self.start_step()
        win.t0 = time.perf_counter() - step_ms / 1000.0
        win.compile_secs = compile_ms / 1000.0
        return self.end_step(**kwargs)

    @contextlib.contextmanager
    def step(self, **extra):
        win = self.start_step()
        win.extra.update(extra)
        try:
            yield win
        finally:
            self.end_step()

    def wrap(self, step_fn):
        """Wrap a train-step callable: every invocation becomes one
        recorded step, the (scalar) return value its loss."""
        def wrapped(*args, **kwargs):
            win = self.start_step()
            try:
                out = step_fn(*args, **kwargs)
                win.note(loss=out)
                return out
            finally:
                self.end_step()
        wrapped.__name__ = getattr(step_fn, "__name__", "step")
        return wrapped

    # -- context activation (TrainStep auto-record) ------------------------
    def __enter__(self):
        # under _LOCK: `current_recorder()` is consulted from other
        # threads (emit_record's fallback chain, span()), and an
        # unlocked append/remove raced those reads
        with _LOCK:
            _RECORDER_STACK.append(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if self._win is not None:
            # abandoned window (the step raised): close it as an aborted
            # record instead of dropping the measurements — the crash
            # file and the JSONL then agree on when the run died
            try:
                self._win.loss = None   # likely poisoned; don't fetch
                self.end_step(aborted=True,
                              abort_reason=(exc_type.__name__
                                            if exc_type else "unknown"))
            except Exception:
                with _LOCK:
                    if self._win in _OPEN_STEPS:
                        _OPEN_STEPS.remove(self._win)
                self._win = None
        with _LOCK:
            _RECORDER_STACK.remove(self)
        if self.sink is not None:
            if self._owns_sink:
                # we opened this file handle; release it (a later write
                # through this recorder transparently reopens append)
                self.sink.close()
            elif hasattr(self.sink, "flush"):
                self.sink.flush()
        return False

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _live_bytes():
        try:
            return int(sum(getattr(a, "nbytes", 0)
                           for a in jax.live_arrays()))
        except Exception:
            return None

    def _collect_collectives(self, span_start):
        """Aggregate this step's wall-time collective spans into the
        per-op breakdown + their total, (coll_or_None, comm_ms). Spans
        tagged traced=true (distributed/collective.py's shard_map
        primitives) cover TRACE time, not communication wall time —
        they stay in the chrome trace but never enter the step record's
        comm attribution."""
        coll, comm_ms = {}, 0.0
        for sp in self.spans[span_start:]:
            if sp.get("cat") != "collective":
                continue
            if (sp.get("args") or {}).get("traced"):
                continue
            ms, calls = coll.get(sp["name"], (0.0, 0))
            dur_ms = sp["dur"] * 1000.0
            coll[sp["name"]] = (ms + dur_ms, calls + 1)
            comm_ms += dur_ms
        return coll or None, comm_ms

    def export_chrome_tracing(self, path, extra_sources=(), align_on=None):
        """Export this recorder's spans (plus any peer ranks') as one
        Chrome trace. See sink.export_chrome_tracing."""
        from .sink import export_chrome_tracing as _export
        return _export(path, [self, *extra_sources], align_on=align_on)
