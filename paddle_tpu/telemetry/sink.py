"""Structured metrics sink: one JSONL record per step + Chrome trace export.

This is the serialization half of the flight recorder. Every consumer of
training/bench metrics in the repo (TelemetryRecorder, TelemetryCallback,
bench.py phases, tools/trace_check.py) speaks the same schema, so a
`BENCH_*.json` entry and a training-run log are directly comparable.

Reference analogs: the profiler's `profiler.proto` serialized output and
`tools/CrossStackProfiler`'s per-rank chrome-trace merge; JAX's
XPlane->TensorBoard path covers device-side detail, this covers the
host-side step ledger.
"""
import json
import os
import threading

SCHEMA_VERSION = 1

# required keys of a per-step record (validated by tools/trace_check.py)
STEP_RECORD_KEYS = ("schema", "kind", "rank", "step", "step_ms",
                    "compile_ms", "execute_ms")
# optional, present when the recorder has the inputs to compute them
STEP_OPTIONAL_KEYS = ("loss", "tokens_per_sec", "mfu", "mem_bytes",
                      "cache_hits", "cache_misses", "collectives", "extra")


def make_step_record(step, step_ms, compile_ms, rank=0, loss=None,
                     tokens_per_sec=None, mfu=None, mem_bytes=None,
                     cache_hits=None, cache_misses=None, collectives=None,
                     **extra):
    """Normalize one step's measurements into the schema dict."""
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "step",
        "rank": int(rank),
        "step": int(step),
        "step_ms": round(float(step_ms), 4),
        "compile_ms": round(float(compile_ms), 4),
        "execute_ms": round(max(0.0, float(step_ms) - float(compile_ms)), 4),
    }
    if loss is not None:
        rec["loss"] = float(loss)
    if tokens_per_sec is not None:
        rec["tokens_per_sec"] = round(float(tokens_per_sec), 2)
    if mfu is not None:
        rec["mfu"] = round(float(mfu), 6)
    if mem_bytes is not None:
        rec["mem_bytes"] = int(mem_bytes)
    if cache_hits is not None:
        rec["cache_hits"] = int(cache_hits)
    if cache_misses is not None:
        rec["cache_misses"] = int(cache_misses)
    if collectives:
        rec["collectives"] = {
            str(k): {"ms": round(float(v[0]), 4), "calls": int(v[1])}
            if isinstance(v, (tuple, list)) else v
            for k, v in collectives.items()}
    if extra:
        rec["extra"] = extra
    return rec


def make_phase_record(phase, metrics, rank=0):
    """A bench-phase record (bench.py): same envelope, kind='phase', the
    phase's metric dict under 'metrics'. Non-finite floats become None —
    json.dumps would otherwise emit bare NaN/Infinity tokens, which are
    invalid for strict JSON consumers (jq, Chrome)."""
    clean = {}
    for k, v in (metrics or {}).items():
        if isinstance(v, float) and (v != v or v in (float("inf"),
                                                     float("-inf"))):
            clean[k] = None
        elif isinstance(v, (int, float)) or v is None or isinstance(v, str):
            clean[k] = v
    return {"schema": SCHEMA_VERSION, "kind": "phase", "rank": int(rank),
            "phase": str(phase), "metrics": clean}


class JsonlSink:
    """Append-only JSONL metrics file, one record per line. Thread-safe;
    flushes per record so a killed run keeps everything written."""

    def __init__(self, path):
        self.path = os.fspath(path)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._mu = threading.Lock()
        self._n = 0

    def write(self, record):
        line = json.dumps(record, sort_keys=True)
        with self._mu:
            with open(self.path, "a") as f:
                f.write(line + "\n")
            self._n += 1
        return record

    def __len__(self):
        return self._n


def read_jsonl(path):
    """Load a metrics JSONL back into a list of dicts (round-trip)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def validate_step_record(rec):
    """Return a list of problems with one record ([] == valid)."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not dict"]
    kind = rec.get("kind")
    if kind == "phase":
        for key in ("schema", "phase", "metrics"):
            if key not in rec:
                problems.append(f"phase record missing '{key}'")
        return problems
    for key in STEP_RECORD_KEYS:
        if key not in rec:
            problems.append(f"step record missing '{key}'")
    for key in ("step_ms", "compile_ms", "execute_ms"):
        v = rec.get(key)
        if v is not None and (not isinstance(v, (int, float)) or v < 0):
            problems.append(f"'{key}' not a non-negative number: {v!r}")
    for key in ("tokens_per_sec", "mfu", "loss"):
        v = rec.get(key)
        if v is not None and not isinstance(v, (int, float)):
            problems.append(f"'{key}' not numeric: {v!r}")
        if isinstance(v, float) and (v != v or v in (float("inf"),
                                                     float("-inf"))):
            problems.append(f"'{key}' non-finite: {v!r}")
    return problems


# ---------------------------------------------------------------------------
# Chrome trace export (CrossStackProfiler analog, multi-rank)
# ---------------------------------------------------------------------------

def spans_to_trace_events(spans, default_rank=0):
    """spans: iterable of dicts {name, t0, dur, rank?, tid?, cat?} (seconds)
    -> chrome trace 'X' events in microseconds, pid == rank."""
    events = []
    ranks = set()
    for sp in spans:
        rank = int(sp.get("rank", default_rank))
        ranks.add(rank)
        events.append({
            "name": sp["name"], "ph": "X",
            "pid": rank, "tid": int(sp.get("tid", 0)),
            "ts": float(sp["t0"]) * 1e6, "dur": float(sp["dur"]) * 1e6,
            "cat": sp.get("cat", "host"),
        })
    meta = [{"name": "process_name", "ph": "M", "pid": r,
             "args": {"name": f"rank {r}"}} for r in sorted(ranks)]
    return meta + events


def export_chrome_tracing(path, sources, align_on=None):
    """Write one Chrome-trace JSON merging host spans across ranks.

    `sources` is a list whose items are either TelemetryRecorder objects
    (their `.spans` and `.rank` are used) or plain span-dict lists. Each
    rank becomes its own trace pid so the merged timeline reads like the
    reference CrossStackProfiler output. `align_on`: optional span name
    whose start is declared t=0 per rank (the `__sync__`-marker recipe
    from tools/merge_profiles.py).

    Returns the number of spans written. Output loads in chrome://tracing
    or Perfetto.
    """
    all_spans = []
    for i, src in enumerate(sources):
        spans = getattr(src, "spans", src)
        rank = getattr(src, "rank", None)
        for sp in spans:
            sp = dict(sp)
            if "rank" not in sp:
                sp["rank"] = i if rank is None else rank
            all_spans.append(sp)
    if align_on is not None:
        zero = {}
        for sp in all_spans:
            if sp["name"] == align_on:
                zero.setdefault(sp["rank"], sp["t0"])
        for sp in all_spans:
            sp["t0"] = sp["t0"] - zero.get(sp["rank"], 0.0)
    events = spans_to_trace_events(all_spans)
    path = os.fspath(path)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return len(all_spans)
