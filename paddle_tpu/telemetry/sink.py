"""Structured metrics sink: one JSONL record per step + Chrome trace export.

This is the serialization half of the flight recorder. Every consumer of
training/bench metrics in the repo (TelemetryRecorder, TelemetryCallback,
bench.py phases, tools/trace_check.py) speaks the same schema, so a
`BENCH_*.json` entry and a training-run log are directly comparable.

Reference analogs: the profiler's `profiler.proto` serialized output and
`tools/CrossStackProfiler`'s per-rank chrome-trace merge; JAX's
XPlane->TensorBoard path covers device-side detail, this covers the
host-side step ledger.
"""
import atexit
import json
import os
import weakref

from ..analysis import lockwatch

# one process-wide atexit hook over weak refs: sinks stay collectable
# (a per-instance atexit.register would pin every sink + its fd for the
# process lifetime) while anything still alive at exit gets flushed
_LIVE_SINKS = weakref.WeakSet()
_ATEXIT_INSTALLED = False


def _close_live_sinks():
    for sink in list(_LIVE_SINKS):
        sink.close()

SCHEMA_VERSION = 1

# required keys of a per-step record (validated by tools/trace_check.py)
STEP_RECORD_KEYS = ("schema", "kind", "rank", "step", "step_ms",
                    "compile_ms", "execute_ms")
# optional, present when the recorder has the inputs to compute them
STEP_OPTIONAL_KEYS = ("loss", "tokens_per_sec", "mfu", "mem_bytes",
                      "cache_hits", "cache_misses", "collectives",
                      "grad_norm", "update_ratio", "nan_count",
                      "inf_count", "input_wait_ms", "input_queue_depth",
                      "input_bound_frac", "moe_entropy",
                      "moe_dropped_frac", "moe_overflow", "moe_aux_loss",
                      "moe_num_experts", "comm_ms", "comm_frac", "extra")
# input-pipeline fields (io.prefetch loader health taps: how long the
# step blocked waiting for its batch, ready-queue depth at fetch, and
# the EMA input-bound fraction — host-bound vs chip-bound as a number)
INPUT_KEYS = ("input_wait_ms", "input_queue_depth", "input_bound_frac")
# health-tap fields (telemetry.health numerics taps; None until a fetch
# step lands them — they appear every k-th record when taps are on)
HEALTH_KEYS = ("grad_norm", "update_ratio", "nan_count", "inf_count")
# MoE routing-health fields (paddle_tpu.moe.stats; present on steps of
# models exposing collect_moe_stats): expert-load entropy (<= log E —
# cross-checked by tools/trace_check.py against moe_num_experts),
# dropped-token fraction in [0, 1], capacity-overflow ratio (>= 0,
# > 1 means some expert saw more assignments than capacity), and the
# load-balancing aux-loss value
MOE_KEYS = ("moe_entropy", "moe_dropped_frac", "moe_overflow",
            "moe_aux_loss", "moe_num_experts")
# communication-attribution fields (telemetry/comm_obs + recorder):
# wall-time collective.* span milliseconds summed over the step
# (trace-time spans tagged traced=true are excluded) and that sum as a
# fraction of step_ms in [0, 1] — compute-vs-communication step
# decomposition as a number; the per-op breakdown stays in
# 'collectives'
COMM_KEYS = ("comm_ms", "comm_frac")

# required keys of a compile-event record (telemetry.compile_obs); the
# optional attachments are hbm (memory_analysis breakdown), cost
# (XLA cost analysis), hlo_ops (top-K opcode table), cause (recompile
# diff strings), signature, hbm_projected_bytes, analytic_flops
COMPILE_RECORD_KEYS = ("schema", "kind", "rank", "fn", "step",
                      "compile_ms", "n_compiles")

# required keys of a checkpoint-event record (paddle_tpu.resilience);
# optional: save_ms, bytes, op, error, problems, removed, signal
CKPT_RECORD_KEYS = ("schema", "kind", "rank", "step", "event")
# the event vocabulary tools/trace_check.py accepts
CKPT_EVENTS = ("save", "commit", "restore", "fallback", "failed", "gc",
               "preempt")

# required keys of an elastic-membership record (distributed.elastic
# ElasticCoordinator + resilience.reshard); optional: host, step,
# miss_count, detect_s, world_from, world_to, layout_from, layout_to,
# dead_hosts
ELASTIC_RECORD_KEYS = ("schema", "kind", "rank", "event")
# the declared-dead protocol's event vocabulary: a host misses a
# heartbeat poll (per miss), is declared dead past the threshold, the
# survivors replan via the auto-sharding planner, the drained
# checkpoint reshards onto the new layout, the process relaunches.
# tools/trace_check.py enforces the cross-record ordering (a
# declared_dead needs a preceding heartbeat_miss for the same host; a
# reshard_restore must reference a committed step and carry BOTH
# layouts; a relaunch needs a preceding replan).
ELASTIC_EVENTS = ("heartbeat_miss", "declared_dead", "replan",
                  "reshard_restore", "relaunch")

# required keys of a serving-lifecycle record (paddle_tpu.serving
# ServingEngine); optional: rid, engine, queue_depth, queue_wait_ms,
# queue_deadline_ms, predicted_wait_ms, retry_after_s, n_tokens,
# priority, reason, error, attempt, requeued, running, completed,
# drained_ms, kv_blocks_used, counts
SERVING_RECORD_KEYS = ("schema", "kind", "rank", "event")
# the request-lifecycle vocabulary: admitted (passed admission control
# into the bounded queue), one of four TERMINAL outcomes (finished /
# failed / cancelled / expired), shed (rejected up front: queue full or
# predicted to blow its deadline — MUST carry queue_depth, the
# pressure that justified the rejection), restart (transient step
# fault -> arenas rebuilt, in-flight requeued for recompute-replay),
# drain_begin/drain_end (graceful drain protocol), quiesce (engine
# idle: counts must balance — admitted == finished+failed+cancelled+
# expired — and kv_blocks_used must be 0; tools/trace_check.py
# enforces both).
SERVING_EVENTS = ("admitted", "finished", "failed", "cancelled",
                  "expired", "shed", "restart", "drain_begin",
                  "drain_end", "quiesce")

# required keys of a per-request trace record (telemetry.reqtrace
# RequestTracer, the serving engine's Dapper-style span timeline);
# optional: engine, t0_s, ttft_ms, tpot_ms, queue_wait_ms, n_tokens,
# prompt_len, preemptions
REQTRACE_RECORD_KEYS = ("schema", "kind", "rank", "rid", "outcome",
                        "e2e_ms", "spans")
# the span vocabulary: queued (waiting; `reason` says why — submit /
# preempt / restart), admit (the admission decision with its prefix-hit
# info), shed (rejected up front), prefill_chunk (one chunked-prefill
# dispatch; `replay`+`replay_cause` mark chunks recomputing positions a
# preemption or warm restart threw away), decode (CONSECUTIVE decode
# steps coalesced into one segment at engine-step boundaries — one span
# per decode stretch, never one per token), preempt / restart_replay
# (the requeue markers), cow_fork (copy-on-write block fork), finalize
# (terminal transition + stream close). Spans TILE the request's
# [submit, finish] wall-clock interval — each begins where the previous
# ended — which is what makes the decomposition invariant (durations
# sum to e2e_ms) checkable by tools/trace_check.py.
# `collective` / `transfer` are the multi-chip vocabulary (ROADMAP
# multi-chip serving item): time inside a cross-chip collective or a
# host<->device / chip<->chip transfer. They tile like every other
# kind, so the decomposition invariant is unchanged — a trace carrying
# them still sums to e2e_ms.
REQTRACE_SPAN_KINDS = ("queued", "admit", "shed", "prefill_chunk",
                       "decode", "preempt", "cow_fork", "restart_replay",
                       "finalize", "collective", "transfer")
# trace outcomes: the four terminal request states plus `shed` (the
# request never entered the engine; its trace is the admission verdict)
REQTRACE_OUTCOMES = ("finished", "failed", "cancelled", "expired",
                     "shed")

# required keys of a fleet-tier record (paddle_tpu.fleet FleetRouter —
# the router/front tier over N engine replicas); optional: replica,
# to_replica, request_id, policy, healthy, miss_count, detect_s,
# breaker, streamed_before, streamed_after, n_tokens, queue_depth,
# retry_after_s, reason, error, counts
FLEET_RECORD_KEYS = ("schema", "kind", "rank", "event")
# the fleet lifecycle vocabulary: route (a routing decision — which
# replica and WHY: prefix_affinity / session / least_loaded), probe
# (one health-probe verdict; an unhealthy probe carries miss_count, the
# ElasticCoordinator consecutive-miss pattern one tier up),
# declared_dead (miss_count consecutive failed probes — must be
# preceded by at least one failed probe for the same replica, the
# elastic declared-dead rule), failover (a request resubmitted after
# replica death or a mid-stream error: must reference a preceding death
# OR carry the error that justified it), replay_spliced (the spliced
# stream's accounting: n_tokens MUST equal streamed_before +
# streamed_after — the recompute-replay invariant made auditable),
# restart (one rolling-restart step: drain -> quiesce -> restart ->
# re-admit for one replica), shed (cross-replica admission rejected the
# request at the fleet door: every replica full/unhealthy), quiesce
# (the fleet ledger snapshot: requests == admitted + shed, and the sum
# of per-replica serving admissions must equal fleet admitted +
# failover re-admissions; tools/trace_check.py enforces all of it).
FLEET_EVENTS = ("route", "probe", "declared_dead", "failover",
                "replay_spliced", "restart", "shed", "quiesce")


def make_step_record(step, step_ms, compile_ms, rank=0, loss=None,
                     tokens_per_sec=None, mfu=None, mem_bytes=None,
                     cache_hits=None, cache_misses=None, collectives=None,
                     grad_norm=None, update_ratio=None, nan_count=None,
                     inf_count=None, input_wait_ms=None,
                     input_queue_depth=None, input_bound_frac=None,
                     moe_entropy=None, moe_dropped_frac=None,
                     moe_overflow=None, moe_aux_loss=None,
                     moe_num_experts=None, comm_ms=None, comm_frac=None,
                     **extra):
    """Normalize one step's measurements into the schema dict."""
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "step",
        "rank": int(rank),
        "step": int(step),
        "step_ms": round(float(step_ms), 4),
        "compile_ms": round(float(compile_ms), 4),
        "execute_ms": round(max(0.0, float(step_ms) - float(compile_ms)), 4),
    }
    if loss is not None:
        rec["loss"] = float(loss)
    if tokens_per_sec is not None:
        rec["tokens_per_sec"] = round(float(tokens_per_sec), 2)
    if mfu is not None:
        rec["mfu"] = round(float(mfu), 6)
    if mem_bytes is not None:
        rec["mem_bytes"] = int(mem_bytes)
    if cache_hits is not None:
        rec["cache_hits"] = int(cache_hits)
    if cache_misses is not None:
        rec["cache_misses"] = int(cache_misses)
    # health taps: keep non-finite values AS IS (NaN round-trips through
    # json.loads) — a poisoned grad_norm is the signal, not noise; the
    # paired nan/inf counts make it machine-checkable regardless
    if grad_norm is not None:
        rec["grad_norm"] = float(grad_norm)
    if update_ratio is not None:
        rec["update_ratio"] = float(update_ratio)
    if nan_count is not None:
        rec["nan_count"] = int(nan_count)
    if inf_count is not None:
        rec["inf_count"] = int(inf_count)
    # input-pipeline taps (io.prefetch): numeric, wait/depth >= 0, the
    # bound fraction in [0, 1] — validated by tools/trace_check.py
    if input_wait_ms is not None:
        rec["input_wait_ms"] = round(float(input_wait_ms), 4)
    if input_queue_depth is not None:
        rec["input_queue_depth"] = int(input_queue_depth)
    if input_bound_frac is not None:
        rec["input_bound_frac"] = round(float(input_bound_frac), 4)
    # MoE routing-health taps (paddle_tpu.moe.stats): bounded fractions
    # + the expert count that anchors the entropy bound — validated
    # below and cross-checked by tools/trace_check.py
    if moe_entropy is not None:
        rec["moe_entropy"] = round(float(moe_entropy), 6)
    if moe_dropped_frac is not None:
        rec["moe_dropped_frac"] = round(float(moe_dropped_frac), 6)
    if moe_overflow is not None:
        rec["moe_overflow"] = round(float(moe_overflow), 6)
    if moe_aux_loss is not None:
        rec["moe_aux_loss"] = round(float(moe_aux_loss), 6)
    if moe_num_experts is not None:
        rec["moe_num_experts"] = int(moe_num_experts)
    # communication attribution (telemetry/comm_obs): wall-time
    # collective span sum + its fraction of the step — validated below
    # and bounded by tools/trace_check.py
    if comm_ms is not None:
        rec["comm_ms"] = round(float(comm_ms), 4)
    if comm_frac is not None:
        rec["comm_frac"] = round(float(comm_frac), 6)
    if collectives:
        rec["collectives"] = {
            str(k): {"ms": round(float(v[0]), 4), "calls": int(v[1])}
            if isinstance(v, (tuple, list)) else v
            for k, v in collectives.items()}
    if extra:
        rec["extra"] = extra
    return rec


def make_compile_record(fn, step, compile_ms, rank=0, n_compiles=1,
                        backend=None, cause=None, signature=None,
                        hbm=None, cost=None, hlo_ops=None,
                        hbm_projected_bytes=None, analytic_flops=None,
                        untracked=False, **extra):
    """One trace/compile event as a first-class record (kind='compile').

    `cause` is the recompile diff (list of human-readable strings) —
    None/absent on the FIRST compile of a signature family, required on
    every later one (tools/trace_check.py enforces this). `untracked`
    marks compiles seen only through the jax.monitoring event stream
    (no signature, so no cause is derivable)."""
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "compile",
        "rank": int(rank),
        "fn": str(fn),
        "step": int(step),
        "compile_ms": round(float(compile_ms), 4),
        "n_compiles": int(n_compiles),
    }
    if backend is not None:
        rec["backend"] = str(backend)
    if cause:
        rec["cause"] = [str(c) for c in cause]
    if signature is not None:
        rec["signature"] = signature
    if hbm:
        rec["hbm"] = {k: int(v) for k, v in hbm.items()
                      if isinstance(v, (int, float))}
    if cost:
        rec["cost"] = {k: float(v) for k, v in cost.items()
                       if isinstance(v, (int, float))}
    if hlo_ops:
        rec["hlo_ops"] = hlo_ops
    if hbm_projected_bytes is not None:
        rec["hbm_projected_bytes"] = int(hbm_projected_bytes)
    if analytic_flops is not None:
        rec["analytic_flops"] = float(analytic_flops)
    if untracked:
        rec["untracked"] = True
    if extra:
        rec["extra"] = extra
    return rec


def make_ckpt_record(event, step, rank=0, save_ms=None, bytes=None,  # noqa: A002
                     **extra):
    """One checkpoint-lifecycle event as a first-class record
    (kind='ckpt', paddle_tpu.resilience.ckpt). `event` is one of
    CKPT_EVENTS: save (async kickoff), commit (manifest + atomic
    rename landed), restore, fallback (a corrupt checkpoint was
    skipped), failed (retries exhausted), gc (retention sweep),
    preempt (graceful-shutdown checkpoint)."""
    if event not in CKPT_EVENTS:
        raise ValueError(f"ckpt event must be one of {CKPT_EVENTS}, "
                         f"got {event!r}")
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "ckpt",
        "rank": int(rank),
        "step": int(step),
        "event": str(event),
    }
    if save_ms is not None:
        rec["save_ms"] = round(float(save_ms), 4)
    if bytes is not None:
        rec["bytes"] = int(bytes)
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


def make_elastic_record(event, rank=0, host=None, step=None,
                        miss_count=None, detect_s=None, world_from=None,
                        world_to=None, layout_from=None, layout_to=None,
                        **extra):
    """One elastic-membership lifecycle event as a first-class record
    (kind='elastic'). `event` is one of ELASTIC_EVENTS; `layout_from`/
    `layout_to` are axis dicts (resilience.reshard.normalize_layout
    canonical form); `detect_s` is the detector's first-miss ->
    declared-dead latency on its own clock (the drill asserts it stays
    inside the configured threshold window)."""
    if event not in ELASTIC_EVENTS:
        raise ValueError(f"elastic event must be one of {ELASTIC_EVENTS}, "
                         f"got {event!r}")
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "elastic",
        "rank": int(rank),
        "event": str(event),
    }
    if host is not None:
        rec["host"] = str(host)
    if step is not None:
        rec["step"] = int(step)
    if miss_count is not None:
        rec["miss_count"] = int(miss_count)
    if detect_s is not None:
        rec["detect_s"] = float(detect_s)
    if world_from is not None:
        rec["world_from"] = int(world_from)
    if world_to is not None:
        rec["world_to"] = int(world_to)
    if layout_from is not None:
        rec["layout_from"] = dict(layout_from)
    if layout_to is not None:
        rec["layout_to"] = dict(layout_to)
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


def make_serving_record(event, rank=0, rid=None, engine=None,
                        queue_depth=None, queue_wait_ms=None,
                        queue_deadline_ms=None, predicted_wait_ms=None,
                        retry_after_s=None, n_tokens=None, priority=None,
                        reason=None, error=None, kv_blocks_used=None,
                        counts=None, **extra):
    """One serving-lifecycle event as a first-class record
    (kind='serving', paddle_tpu.serving.ServingEngine). `event` is one
    of SERVING_EVENTS; `engine` is the emitting engine instance id (so
    one ledger can carry several sequential engines and the quiesce
    accounting stays per-engine); `counts` is the quiesce snapshot of
    the engine's request accounting."""
    if event not in SERVING_EVENTS:
        raise ValueError(f"serving event must be one of {SERVING_EVENTS}, "
                         f"got {event!r}")
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "serving",
        "rank": int(rank),
        "event": str(event),
    }
    if rid is not None:
        rec["rid"] = int(rid)
    if engine is not None:
        rec["engine"] = int(engine)
    if queue_depth is not None:
        rec["queue_depth"] = int(queue_depth)
    if queue_wait_ms is not None:
        rec["queue_wait_ms"] = round(float(queue_wait_ms), 4)
    if queue_deadline_ms is not None:
        rec["queue_deadline_ms"] = round(float(queue_deadline_ms), 4)
    if predicted_wait_ms is not None:
        rec["predicted_wait_ms"] = round(float(predicted_wait_ms), 4)
    if retry_after_s is not None:
        rec["retry_after_s"] = round(float(retry_after_s), 4)
    if n_tokens is not None:
        rec["n_tokens"] = int(n_tokens)
    if priority is not None:
        rec["priority"] = str(priority)
    if reason is not None:
        rec["reason"] = str(reason)
    if error is not None:
        rec["error"] = str(error)
    if kv_blocks_used is not None:
        rec["kv_blocks_used"] = int(kv_blocks_used)
    if counts is not None:
        rec["counts"] = {str(k): int(v) for k, v in counts.items()}
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


def make_reqtrace_record(rid, outcome, spans, e2e_ms, rank=0, engine=None,
                         t0_s=None, ttft_ms=None, tpot_ms=None,
                         queue_wait_ms=None, n_tokens=None,
                         prompt_len=None, preemptions=None, **extra):
    """One request's complete span timeline as a first-class record
    (kind='reqtrace', telemetry.reqtrace.RequestTracer). `spans` is the
    ordered tiling of the request's wall-clock life — each span a dict
    {kind, t0_ms, dur_ms, ...attrs} with t0_ms relative to submit time —
    and `e2e_ms` the end-to-end latency the span durations must sum to
    (tools/trace_check.py enforces the decomposition within 1%).
    `t0_s` is the submit instant on the process monotonic clock, which
    is what lets offline tools order requests and the Chrome export
    place per-request lanes next to engine-step spans."""
    if outcome not in REQTRACE_OUTCOMES:
        raise ValueError(f"reqtrace outcome must be one of "
                         f"{REQTRACE_OUTCOMES}, got {outcome!r}")
    norm = []
    for sp in spans:
        s = {"kind": str(sp["kind"]),
             "t0_ms": round(float(sp["t0_ms"]), 4),
             "dur_ms": round(float(sp["dur_ms"]), 4)}
        for k, v in sp.items():
            if k not in ("kind", "t0_ms", "dur_ms") and v is not None:
                s[k] = v
        norm.append(s)
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "reqtrace",
        "rank": int(rank),
        "rid": int(rid),
        "outcome": str(outcome),
        "e2e_ms": round(float(e2e_ms), 4),
        "spans": norm,
    }
    if engine is not None:
        rec["engine"] = int(engine)
    if t0_s is not None:
        rec["t0_s"] = round(float(t0_s), 6)
    if ttft_ms is not None:
        rec["ttft_ms"] = round(float(ttft_ms), 4)
    if tpot_ms is not None:
        rec["tpot_ms"] = round(float(tpot_ms), 4)
    if queue_wait_ms is not None:
        rec["queue_wait_ms"] = round(float(queue_wait_ms), 4)
    if n_tokens is not None:
        rec["n_tokens"] = int(n_tokens)
    if prompt_len is not None:
        rec["prompt_len"] = int(prompt_len)
    if preemptions is not None:
        rec["preemptions"] = int(preemptions)
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


def make_fleet_record(event, rank=0, replica=None, to_replica=None,
                      request_id=None, policy=None, healthy=None,
                      miss_count=None, detect_s=None, breaker=None,
                      streamed_before=None, streamed_after=None,
                      n_tokens=None, queue_depth=None, retry_after_s=None,
                      reason=None, error=None, counts=None, **extra):
    """One fleet-tier event as a first-class record (kind='fleet',
    paddle_tpu.fleet.FleetRouter). `event` is one of FLEET_EVENTS;
    `replica` names the replica the event is ABOUT (for a failover,
    the one that failed — `to_replica` is where the request went);
    `request_id` is the stable client-visible id that joins fleet
    records to the per-replica kind=serving / kind=reqtrace records;
    `counts` is the quiesce snapshot of the router's accounting."""
    if event not in FLEET_EVENTS:
        raise ValueError(f"fleet event must be one of {FLEET_EVENTS}, "
                         f"got {event!r}")
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "fleet",
        "rank": int(rank),
        "event": str(event),
    }
    if replica is not None:
        rec["replica"] = str(replica)
    if to_replica is not None:
        rec["to_replica"] = str(to_replica)
    if request_id is not None:
        rec["request_id"] = str(request_id)
    if policy is not None:
        rec["policy"] = str(policy)
    if healthy is not None:
        rec["healthy"] = bool(healthy)
    if miss_count is not None:
        rec["miss_count"] = int(miss_count)
    if detect_s is not None:
        rec["detect_s"] = round(float(detect_s), 4)
    if breaker is not None:
        rec["breaker"] = str(breaker)
    if streamed_before is not None:
        rec["streamed_before"] = int(streamed_before)
    if streamed_after is not None:
        rec["streamed_after"] = int(streamed_after)
    if n_tokens is not None:
        rec["n_tokens"] = int(n_tokens)
    if queue_depth is not None:
        rec["queue_depth"] = int(queue_depth)
    if retry_after_s is not None:
        rec["retry_after_s"] = round(float(retry_after_s), 4)
    if reason is not None:
        rec["reason"] = str(reason)
    if error is not None:
        rec["error"] = str(error)
    if counts is not None:
        rec["counts"] = {str(k): int(v) for k, v in counts.items()}
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


BENCH_RECORD_KEYS = ("schema", "kind", "metric", "value")

# the SERVING bench-metric family (bench_serving.py over
# paddle_tpu/serving): one source of truth for metric names + gate
# directions so the bench emitter, the rolling baseline
# (tools/bench_baseline.json), and tools/trace_check.py's serving
# cross-rules cannot drift. Directions are the bench_gate vocabulary:
# 'higher' fails when the value drops, 'lower' when it rises (latency),
# 'info' is recorded but never gated.
SERVING_BENCH_METRICS = {
    "serving.single_stream_tokens_per_sec": "higher",
    "serving.throughput_tokens_per_sec": "higher",
    "serving.throughput_vs_single": "higher",
    "serving.ttft_p50_ms": "lower",
    "serving.ttft_p99_ms": "lower",
    "serving.tpot_p50_ms": "lower",
    "serving.tpot_p99_ms": "lower",
    "serving.requests": "info",
    "serving.preemptions": "info",
    "serving.kv_block_utilization_peak": "info",
    # the serving-resilience drill's rated-load leg (tools/
    # serving_drill.py --rated-only): throughput at rated load with SLO
    # deadlines armed, queue-wait p99 under admission control, and the
    # shed count — direction 'lower' over a 0.0 baseline means ANY shed
    # at rated load fails the gate (the SLO sweep must run shed-free)
    "serving.rated_throughput_tokens_per_sec": "higher",
    "serving.rated_queue_wait_ms_p99": "lower",
    "serving.rated_shed": "lower",
    # the prefix-sharing sweep (bench_serving.py shared-prefix phase):
    # N requests over K templates through a warm prefix-cache engine
    # vs a cold-cache control with identical token streams. hit_rate
    # and tokens_saved are deterministic for the seeded workload
    # (direction 'higher': a drop means the matcher stopped finding
    # prefixes it used to); tokens_offered is the denominator that
    # makes tokens_saved auditable (info); the TTFT rows quote the
    # WARM engine, and the speedup row is warm-vs-cold at p50 — the
    # whole point of the cache
    "serving.prefix_hit_rate": "higher",
    "serving.prefill_tokens_saved": "higher",
    "serving.prefill_tokens_offered": "info",
    "serving.prefix_ttft_p50_ms": "lower",
    "serving.prefix_ttft_p99_ms": "lower",
    "serving.prefix_ttft_speedup": "higher",
    "serving.prefix_tokens_recomputed_per_request": "lower",
    # the request tracer's cost (bench_serving.py trace_overhead_phase):
    # rated-level throughput with tracing on vs off as a fraction lost,
    # direction 'lower' so bench_gate holds the tracer to its <=2%
    # budget once a device round seeds the row — a tracer that starts
    # doing per-token host work fails the gate like any regression
    "serving.trace_overhead_frac": "lower",
    # the fleet-tier rated leg (bench_serving.py --fleet N): aggregate
    # rated throughput over N replicas, and scaling efficiency —
    # aggregate / (N x the single-replica rated figure measured in the
    # same run). Direction 'higher' on both: a router whose efficiency
    # decays is paying routing/affinity overhead the ROADMAP's
    # ~linear-scaling target does not allow. replicas is the
    # denominator that makes the efficiency row auditable (info).
    "fleet.rated_throughput_tokens_per_sec": "higher",
    "fleet.scaling_efficiency": "higher",
    "fleet.replicas": "info",
}

# required keys of a Kernel Doctor result record (analysis/kernel_lint
# via tools/kerneldoctor.py); optional: module, fn, grid, vmem_bytes,
# vmem_budget, flops_declared, flops_counted, has_fallback
KERNEL_RECORD_KEYS = ("schema", "kind", "rank", "kernel", "n_findings",
                      "findings")

# the KN rule vocabulary (analysis/kernel_lint.RULES is the documented
# source; this tuple is what the record validator enforces)
KERNEL_LINT_RULES = ("KN501", "KN502", "KN503", "KN504", "KN505")


def make_kernel_record(kernel, findings=(), rank=0, module=None,
                       fn=None, grid=None, vmem_bytes=None,
                       vmem_budget=None, flops_declared=None,
                       flops_counted=None, has_fallback=None, **extra):
    """One kernel's Kernel Doctor verdict as a first-class record
    (kind='kernel_lint'). `findings` is a list of Finding objects or
    {rule, message} dicts; a clean kernel records n_findings == 0 with
    its derived numbers (grid, projected VMEM, declared-vs-counted
    FLOPs) so the ledger shows what was checked, not just that nothing
    fired. tools/trace_check.py cross-checks the numbers against the
    findings (a VMEM projection over budget with no KN502 finding is a
    doctored or half-written ledger)."""
    fs = []
    for f in findings:
        if isinstance(f, dict):
            fs.append({"rule": str(f.get("rule", "")),
                       "message": str(f.get("message", ""))})
        else:
            fs.append({"rule": str(getattr(f, "rule_id", "")),
                       "message": str(getattr(f, "message", ""))})
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "kernel_lint",
        "rank": int(rank),
        "kernel": str(kernel),
        "n_findings": len(fs),
        "findings": fs,
    }
    if module is not None:
        rec["module"] = str(module)
    if fn is not None:
        rec["fn"] = str(fn)
    if grid is not None:
        rec["grid"] = [int(g) for g in grid]
    if vmem_bytes is not None:
        rec["vmem_bytes"] = int(vmem_bytes)
    if vmem_budget is not None:
        rec["vmem_budget"] = int(vmem_budget)
    if flops_declared is not None:
        rec["flops_declared"] = int(flops_declared)
    if flops_counted is not None:
        rec["flops_counted"] = int(flops_counted)
    if has_fallback is not None:
        rec["has_fallback"] = bool(has_fallback)
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


# required keys of a Concurrency Doctor record (analysis/threadlint +
# analysis/lockwatch via tools/threaddoctor.py); optional: locks,
# n_locks, modules
THREAD_LINT_RECORD_KEYS = ("schema", "kind", "rank", "source",
                           "n_findings", "findings", "n_edges", "edges")

# the TH rule vocabulary (analysis/threadlint's docstring is the
# documented source; this tuple is what the record validator enforces)
THREAD_LINT_RULES = ("TH600", "TH601", "TH602", "TH603", "TH604")

# what a thread_lint record may claim to be: the static pass over the
# source, or the lockwatch runtime witness
THREAD_LINT_SOURCES = ("static", "lockwatch")


def make_thread_lint_record(source, findings=(), edges=(), rank=0,
                            locks=None, modules=None, **extra):
    """One Concurrency Doctor verdict as a first-class record
    (kind='thread_lint'). source='static' carries threadlint's findings
    plus the nested-acquisition graph edges ([held, acquired, site]);
    source='lockwatch' carries the runtime witness — observed
    acquisition-order edges ([held, acquired, count]) and the per-lock
    snapshot under 'locks' (the watchdog black-box section).
    tools/trace_check.py cross-rules a static/lockwatch pair in the
    same file: the observed edge set must be a SUBGRAPH of the static
    graph, and any observed cycle fails outright."""
    fs = []
    for f in findings:
        if isinstance(f, dict):
            fs.append({"rule": str(f.get("rule", "")),
                       "message": str(f.get("message", ""))})
        else:
            fs.append({"rule": str(getattr(f, "rule_id", "")),
                       "message": str(getattr(f, "message", ""))})
    es = [[e[0], e[1], e[2]] for e in edges]
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "thread_lint",
        "rank": int(rank),
        "source": str(source),
        "n_findings": len(fs),
        "findings": fs,
        "n_edges": len(es),
        "edges": es,
    }
    if locks is not None:
        rec["locks"] = [dict(row) for row in locks]
        rec["n_locks"] = len(rec["locks"])
    if modules is not None:
        rec["modules"] = [str(m) for m in modules]
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


# required keys of a kernel-observatory measurement record
# (telemetry/kernel_obs via tools/kernellab.py); optional: dtype,
# fallback_ms, speedup, compile_ms, flops, bytes_accessed, flops_frac,
# bw_frac, predicted_ms, bound, config, db_key, n_samples, warmup,
# event, seed
KERNELBENCH_RECORD_KEYS = ("schema", "kind", "rank", "kernel", "sig",
                           "backend", "kernel_ms")

# what one kernelbench record may claim to be (cross-checked by
# tools/trace_check.py: a db_update must reference a measured row)
KERNELBENCH_EVENTS = ("measure", "tune", "db_update")


def make_kernelbench_record(kernel, sig, backend, kernel_ms, rank=0,
                            dtype=None, fallback_ms=None, speedup=None,
                            compile_ms=None, flops=None,
                            bytes_accessed=None, flops_frac=None,
                            bw_frac=None, predicted_ms=None, bound=None,
                            config=None, db_key=None, n_samples=None,
                            warmup=None, event=None, seed=None, **extra):
    """One measured kernel data point as a first-class typed record
    (kind='kernelbench') — the dynamic sibling of kind='kernel_lint':
    the Kernel Doctor records what a kernel IS, the observatory records
    how fast it RAN. `sig` + `dtype` + `backend` reproduce the DB key
    (telemetry/kernel_obs.db_key); `kernel_ms` is the compile-excluded
    execute median (compile_ms rides separately, the PR-4 split);
    roofline fractions are achieved/peak in [0, 1]; `predicted_ms` is
    the roofline floor the kernel_time_drift rule judges against.
    Non-finite timings become None + an error note, like
    make_bench_record — the validators fail them loudly rather than
    letting a NaN ride the ledger."""
    def _clean(v):
        if v is None:
            return None, False
        bad = isinstance(v, float) and (v != v or v in (float("inf"),
                                                        float("-inf")))
        return (None if bad else float(v)), bad

    kernel_ms, bad = _clean(kernel_ms)
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "kernelbench",
        "rank": int(rank),
        "kernel": str(kernel),
        "sig": str(sig),
        "backend": str(backend),
        "kernel_ms": kernel_ms,
    }
    if bad:
        rec["error"] = "non-finite kernel_ms"
    if dtype is not None:
        rec["dtype"] = str(dtype)
    for key, v in (("fallback_ms", fallback_ms), ("speedup", speedup),
                   ("compile_ms", compile_ms),
                   ("flops_frac", flops_frac), ("bw_frac", bw_frac),
                   ("predicted_ms", predicted_ms)):
        v, bad = _clean(v)
        if v is not None:
            rec[key] = v
        elif bad:
            rec["error"] = f"non-finite {key}"
    if flops is not None:
        rec["flops"] = int(flops)
    if bytes_accessed is not None:
        rec["bytes_accessed"] = int(bytes_accessed)
    if bound is not None:
        rec["bound"] = str(bound)
    if config is not None:
        rec["config"] = dict(config)
    if db_key is not None:
        rec["db_key"] = str(db_key)
    if n_samples is not None:
        rec["n_samples"] = int(n_samples)
    if warmup is not None:
        rec["warmup"] = int(warmup)
    if event is not None:
        rec["event"] = str(event)
    if seed is not None:
        rec["seed"] = int(seed)
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


# required keys of a mesh-observatory measurement record
# (telemetry/comm_obs via tools/commlab.py); optional: compile_ms,
# wire_bytes, achieved_bw, peak_bw, bw_frac, predicted_ms, db_ms,
# db_key, medium, n_samples, warmup, event, seed
COMMBENCH_RECORD_KEYS = ("schema", "kind", "rank", "op", "axis",
                         "axis_size", "payload_bytes", "backend",
                         "time_ms")

# the sweep's op vocabulary — the shard_map collectives
# distributed/collective.py issues (telemetry/comm_obs.SWEEP_OPS)
COMMBENCH_OPS = ("psum", "all_gather", "reduce_scatter", "all_to_all",
                 "ppermute")

# what one commbench record may claim to be (cross-checked by
# tools/trace_check.py: a db_update must reference a measured row)
COMMBENCH_EVENTS = ("measure", "db_update")


def make_commbench_record(op, axis, axis_size, payload_bytes, backend,
                          time_ms, rank=0, compile_ms=None,
                          wire_bytes=None, achieved_bw=None, peak_bw=None,
                          bw_frac=None, predicted_ms=None, db_ms=None,
                          db_key=None, medium=None, n_samples=None,
                          warmup=None, event=None, seed=None, **extra):
    """One measured collective data point as a first-class typed record
    (kind='commbench') — the communication sibling of kind='kernelbench':
    the kernel observatory measures what one chip computes, the mesh
    observatory measures what the mesh moves. `op` + `axis_size` +
    `payload_bytes` + `backend` reproduce the DB key
    (telemetry/comm_obs.db_key); `time_ms` is the compile-excluded
    execute median (compile_ms rides separately); `achieved_bw` /
    `bw_frac` place it against the planner's `ICI_BW_BY_CHIP` /
    `DCN_BW_BYTES` peaks; `predicted_ms` is the analytic floor
    `calibration_from_comm_records` ratios against; `db_ms` is the
    best-known DB latency the comm_bw_degraded rule judges against
    (absent when the PADDLE_TPU_COMM_DB flag is off — no reference, no
    jurisdiction). Non-finite timings become None + an error note, like
    make_kernelbench_record — a NaN never rides the ledger silently."""
    def _clean(v):
        if v is None:
            return None, False
        bad = isinstance(v, float) and (v != v or v in (float("inf"),
                                                        float("-inf")))
        return (None if bad else float(v)), bad

    time_ms, bad = _clean(time_ms)
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "commbench",
        "rank": int(rank),
        "op": str(op),
        "axis": str(axis),
        "axis_size": int(axis_size),
        "payload_bytes": int(payload_bytes),
        "backend": str(backend),
        "time_ms": time_ms,
    }
    if bad:
        rec["error"] = "non-finite time_ms"
    for key, v in (("compile_ms", compile_ms), ("wire_bytes", wire_bytes),
                   ("achieved_bw", achieved_bw), ("peak_bw", peak_bw),
                   ("bw_frac", bw_frac), ("predicted_ms", predicted_ms),
                   ("db_ms", db_ms)):
        v, bad = _clean(v)
        if v is not None:
            rec[key] = v
        elif bad:
            rec["error"] = f"non-finite {key}"
    if db_key is not None:
        rec["db_key"] = str(db_key)
    if medium is not None:
        rec["medium"] = str(medium)
    if n_samples is not None:
        rec["n_samples"] = int(n_samples)
    if warmup is not None:
        rec["warmup"] = int(warmup)
    if event is not None:
        rec["event"] = str(event)
    if seed is not None:
        rec["seed"] = int(seed)
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


# required keys of a memory-observatory ledger record
# (telemetry/mem_obs via tools/memwatch.py); optional: the attribution
# buckets, budget/headroom/projection anchors, KV-pool accounting, and
# the postmortem payload (top_arrays, compile_families)
MEMSNAP_RECORD_KEYS = ("schema", "kind", "rank", "event", "step",
                       "total_bytes")

# attribution buckets — every live byte lands in exactly ONE, so
# tools/trace_check.py can recompute total_bytes from the record's own
# fields (the reqtrace decomposition stance, applied to HBM)
MEMSNAP_BUCKETS = ("params_bytes", "opt_state_bytes", "kv_bytes",
                   "workspace_bytes", "other_bytes")

# what one memsnap record may claim to be: a step-cadence ledger
# snapshot, or the capture-on-failure POSTMORTEM written when an
# allocation failed (RESOURCE_EXHAUSTED) — a postmortem must carry an
# error note and the top-K array listing (validated below), so an OOM
# is diagnosable offline from the ledger alone
MEMSNAP_EVENTS = ("snapshot", "postmortem")


def make_memsnap_record(event, step, total_bytes, rank=0,
                        params_bytes=None, opt_state_bytes=None,
                        kv_bytes=None, workspace_bytes=None,
                        other_bytes=None, hbm_budget_bytes=None,
                        headroom_bytes=None, projected_bytes=None,
                        projection_family=None, n_arrays=None,
                        kv_blocks_total=None, kv_blocks_held=None,
                        kv_blocks_free=None, kv_blocks_cached=None,
                        kv_occupancy=None, kv_cache_share=None,
                        kv_evictions=None, kv_admissions=None,
                        kv_eviction_rate=None, kv_admission_rate=None,
                        evictions_by_class=None, admissions_by_class=None,
                        engine=None, error=None, top_arrays=None,
                        compile_families=None, **extra):
    """One live-HBM ledger snapshot as a first-class typed record
    (kind='memsnap') — the memory sibling of kind='commbench': the mesh
    observatory measures what the mesh moves, the memory observatory
    measures what the chip HOLDS. The bucket fields (MEMSNAP_BUCKETS)
    partition total_bytes — tools/trace_check.py recomputes the sum;
    `headroom_bytes` is max(0, hbm_budget_bytes - total_bytes), the
    admission signal the serving engine gauges; `projected_bytes` is
    the compile observatory's static memory_analysis() projection the
    reconcile-drift rule latches against; the kv_* fields snapshot the
    BlockPool/PrefixIndex accounting (held+free+cached must tile
    kv_blocks_total) plus the eviction/admission rates the kv_thrash
    rule judges — all riding ON the record, so healthwatch replay and
    the in-flight detector see identical numbers. A postmortem event
    additionally carries `error`, the top-K `top_arrays` by bytes, and
    the active `compile_families`. Non-finite measurements become None
    + an error note, like make_commbench_record — a NaN never rides
    the ledger silently."""
    def _clean(v):
        if v is None:
            return None, False
        bad = isinstance(v, float) and (v != v or v in (float("inf"),
                                                        float("-inf")))
        return (None if bad else float(v)), bad

    total_bytes, bad = _clean(total_bytes)
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "memsnap",
        "rank": int(rank),
        "event": str(event),
        "step": int(step),
        "total_bytes": None if total_bytes is None else int(total_bytes),
    }
    if bad:
        rec["error"] = "non-finite total_bytes"
    for key, v in (("params_bytes", params_bytes),
                   ("opt_state_bytes", opt_state_bytes),
                   ("kv_bytes", kv_bytes),
                   ("workspace_bytes", workspace_bytes),
                   ("other_bytes", other_bytes),
                   ("hbm_budget_bytes", hbm_budget_bytes),
                   ("headroom_bytes", headroom_bytes),
                   ("projected_bytes", projected_bytes)):
        v, bad = _clean(v)
        if v is not None:
            rec[key] = int(v)
        elif bad:
            rec["error"] = f"non-finite {key}"
    for key, v in (("kv_occupancy", kv_occupancy),
                   ("kv_cache_share", kv_cache_share),
                   ("kv_eviction_rate", kv_eviction_rate),
                   ("kv_admission_rate", kv_admission_rate)):
        v, bad = _clean(v)
        if v is not None:
            rec[key] = round(v, 6)
        elif bad:
            rec["error"] = f"non-finite {key}"
    for key, v in (("n_arrays", n_arrays),
                   ("kv_blocks_total", kv_blocks_total),
                   ("kv_blocks_held", kv_blocks_held),
                   ("kv_blocks_free", kv_blocks_free),
                   ("kv_blocks_cached", kv_blocks_cached),
                   ("kv_evictions", kv_evictions),
                   ("kv_admissions", kv_admissions),
                   ("engine", engine)):
        if v is not None:
            rec[key] = int(v)
    if projection_family is not None:
        rec["projection_family"] = str(projection_family)
    if evictions_by_class is not None:
        rec["evictions_by_class"] = {str(k): int(v) for k, v
                                     in evictions_by_class.items()}
    if admissions_by_class is not None:
        rec["admissions_by_class"] = {str(k): int(v) for k, v
                                      in admissions_by_class.items()}
    if error is not None:
        rec["error"] = str(error)
    if top_arrays is not None:
        rec["top_arrays"] = list(top_arrays)
    if compile_families is not None:
        rec["compile_families"] = list(compile_families)
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


# required keys of an auto-sharding plan record (paddle_tpu.planner);
# optional: chip, n_chips, projected_hbm_bytes, measured_hbm_bytes,
# hbm_budget_bytes, cost_step_s, calibration, verify
PLAN_RECORD_KEYS = ("schema", "kind", "rank", "model", "chosen",
                    "candidates_considered", "candidates_rejected")


def make_plan_record(model, chosen, candidates_considered,
                     candidates_rejected, rank=0, chip=None, n_chips=None,
                     projected_hbm_bytes=None, measured_hbm_bytes=None,
                     hbm_budget_bytes=None, cost_step_s=None,
                     calibration=None, verify=None, **extra):
    """One auto-sharding decision as a first-class record (kind='plan',
    paddle_tpu.planner.Plan.to_record). `chosen` is the layout dict
    (dp/pp/mp/sp/ep/zero_stage/...); `candidates_rejected` is the
    rejection ledger ([{layout, reason}] — every reason non-empty, the
    validator enforces it). `measured_hbm_bytes` is attached after the
    compile observatory measures the chosen layout's first compile;
    tools/trace_check.py fails the plan when measured drifts >15% from
    `projected_hbm_bytes` (the PR-4 hbm_projection_drift rule applied
    to the planner's own numbers)."""
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "plan",
        "rank": int(rank),
        "model": str(model),
        "chosen": dict(chosen),
        "candidates_considered": int(candidates_considered),
        "candidates_rejected": [dict(r) for r in candidates_rejected],
    }
    if chip is not None:
        rec["chip"] = str(chip)
    if n_chips is not None:
        rec["n_chips"] = int(n_chips)
    if projected_hbm_bytes is not None:
        rec["projected_hbm_bytes"] = int(projected_hbm_bytes)
    if measured_hbm_bytes is not None:
        rec["measured_hbm_bytes"] = int(measured_hbm_bytes)
    if hbm_budget_bytes is not None:
        rec["hbm_budget_bytes"] = int(hbm_budget_bytes)
    if cost_step_s is not None:
        rec["cost_step_s"] = float(cost_step_s)
    if calibration is not None:
        rec["calibration"] = float(calibration)
    if verify is not None:
        rec["verify"] = verify
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


def make_bench_record(metric, value, unit=None, rank=0, device=None,
                      bench_round=None, baseline=None, **extra):
    """One benchmark RESULT as a first-class typed record (kind='bench')
    — the perf-regression gate's unit of account (tools/bench_gate.py).
    Distinct from kind='phase' (a phase's raw metric dict): a bench
    record is one tracked scalar with its identity (metric name, device,
    round) so baselines diff record-against-record. Non-finite values
    are kept as None + an error note (the gate fails them loudly)."""
    rec = {
        "schema": SCHEMA_VERSION,
        "kind": "bench",
        "rank": int(rank),
        "metric": str(metric),
    }
    bad = isinstance(value, float) and (value != value or
                                        value in (float("inf"),
                                                  float("-inf")))
    rec["value"] = None if bad or value is None else float(value)
    if bad:
        rec["error"] = f"non-finite value {value!r}"
    if unit is not None:
        rec["unit"] = str(unit)
    if device is not None:
        rec["device"] = str(device)
    if bench_round is not None:
        rec["round"] = int(bench_round)
    if baseline is not None:
        rec["baseline"] = float(baseline)
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


def make_phase_record(phase, metrics, rank=0):
    """A bench-phase record (bench.py): same envelope, kind='phase', the
    phase's metric dict under 'metrics'. Non-finite floats become None —
    json.dumps would otherwise emit bare NaN/Infinity tokens, which are
    invalid for strict JSON consumers (jq, Chrome)."""
    clean = {}
    for k, v in (metrics or {}).items():
        if isinstance(v, float) and (v != v or v in (float("inf"),
                                                     float("-inf"))):
            clean[k] = None
        elif isinstance(v, (int, float)) or v is None or isinstance(v, str):
            clean[k] = v
    return {"schema": SCHEMA_VERSION, "kind": "phase", "rank": int(rank),
            "phase": str(phase), "metrics": clean}


class JsonlSink:
    """Append-only JSONL metrics file, one record per line. Thread-safe.

    Crash durability: the file handle is held open and every record is
    flushed to the OS as it is written, and live sinks are closed by a
    process-wide `atexit` hook (weak refs — a sink is still collectable
    the moment its owner drops it) — records buffered at the moment of
    an exception (or a SystemExit tearing the interpreter down) are on
    disk, not lost in a dead buffer. A write after close() transparently
    reopens (append), so a closed sink still works."""

    def __init__(self, path):
        global _ATEXIT_INSTALLED
        self.path = os.fspath(path)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._mu = lockwatch.make_lock("JsonlSink._mu")
        self._n = 0     # guarded by: _mu
        self._f = open(self.path, "a")  # guarded by: _mu
        if not _ATEXIT_INSTALLED:
            atexit.register(_close_live_sinks)
            _ATEXIT_INSTALLED = True
        _LIVE_SINKS.add(self)

    def write(self, record):
        line = json.dumps(record, sort_keys=True)
        with self._mu:
            if self._f is None or self._f.closed:
                self._f = open(self.path, "a")
            self._f.write(line + "\n")
            self._f.flush()
            self._n += 1
        return record

    def flush(self):
        with self._mu:
            if self._f is not None and not self._f.closed:
                self._f.flush()
                try:
                    os.fsync(self._f.fileno())
                except OSError:
                    pass

    def close(self):
        with self._mu:
            if self._f is not None and not self._f.closed:
                self._f.flush()
                self._f.close()

    def __len__(self):  # threadlint: lock-free (racy record count is fine for progress/tests)
        return self._n


def emit_record(rec, *sinks):
    """Write one record through THE standard sink fallback chain —
    the first usable candidate wins, else the context-active
    recorder's sink, else the record is returned unwritten. Each
    candidate may be a sink object (anything with .write), a path
    string (opened append as a JsonlSink), or None. This is the single
    owner of the precedence rule the resilience/elastic emitters share
    (explicit sink > manager's sink > active recorder)."""
    out = None
    for s in sinks:
        if s is None:
            continue
        out = JsonlSink(s) if isinstance(s, str) else s
        break
    if out is None:
        from .recorder import current_recorder
        r = current_recorder()
        out = r.sink if r is not None else None
    if out is not None:
        out.write(rec)
    return rec


def read_jsonl(path):
    """Load a metrics JSONL back into a list of dicts (round-trip)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def validate_step_record(rec):
    """Return a list of problems with one record ([] == valid)."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not dict"]
    kind = rec.get("kind")
    if kind == "phase":
        for key in ("schema", "phase", "metrics"):
            if key not in rec:
                problems.append(f"phase record missing '{key}'")
        return problems
    if kind == "compile":
        for key in COMPILE_RECORD_KEYS:
            if key not in rec:
                problems.append(f"compile record missing '{key}'")
        v = rec.get("compile_ms")
        if v is not None and (not isinstance(v, (int, float)) or v < 0):
            problems.append(f"'compile_ms' not a non-negative number: {v!r}")
        n = rec.get("n_compiles")
        if n is not None and (not isinstance(n, int) or n < 1):
            problems.append(f"'n_compiles' not a positive int: {n!r}")
        cause = rec.get("cause")
        if cause is not None and (not isinstance(cause, list) or
                                  not all(isinstance(c, str) for c in cause)):
            problems.append(f"'cause' not a list of strings: {cause!r}")
        return problems
    if kind == "bench":
        for key in BENCH_RECORD_KEYS:
            if key not in rec:
                problems.append(f"bench record missing '{key}'")
        v = rec.get("value")
        if v is not None and not isinstance(v, (int, float)):
            problems.append(f"'value' not numeric: {v!r}")
        if isinstance(v, float) and (v != v or v in (float("inf"),
                                                     float("-inf"))):
            problems.append(f"'value' non-finite: {v!r}")
        if v is None and "error" not in rec:
            problems.append("bench record with null value carries no "
                            "'error' note")
        return problems
    if kind == "kernel_lint":
        for key in KERNEL_RECORD_KEYS:
            if key not in rec:
                problems.append(f"kernel_lint record missing '{key}'")
        if not str(rec.get("kernel", "")).strip():
            problems.append("kernel_lint record names no kernel")
        n = rec.get("n_findings")
        fs = rec.get("findings")
        if n is not None and (not isinstance(n, int) or n < 0):
            problems.append(f"'n_findings' not a non-negative int: {n!r}")
        if fs is not None:
            if not isinstance(fs, list):
                problems.append("'findings' not a list")
            else:
                if isinstance(n, int) and n != len(fs):
                    problems.append(
                        f"n_findings {n} but {len(fs)} findings listed "
                        "— the count and the list disagree")
                for j, f in enumerate(fs):
                    if not isinstance(f, dict):
                        problems.append(f"finding {j} not a dict")
                        continue
                    if f.get("rule") not in KERNEL_LINT_RULES:
                        problems.append(
                            f"finding {j} rule {f.get('rule')!r} not in "
                            f"the KN vocabulary "
                            f"{list(KERNEL_LINT_RULES)}")
                    if not str(f.get("message", "")).strip():
                        problems.append(
                            f"finding {j} carries no message — a "
                            "finding the ledger cannot explain")
        for key in ("vmem_bytes", "vmem_budget", "flops_declared",
                    "flops_counted"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v < 0):
                problems.append(
                    f"'{key}' not a non-negative number: {v!r}")
        return problems
    if kind == "thread_lint":
        for key in THREAD_LINT_RECORD_KEYS:
            if key not in rec:
                problems.append(f"thread_lint record missing '{key}'")
        src = rec.get("source")
        if src is not None and src not in THREAD_LINT_SOURCES:
            problems.append(
                f"unknown thread_lint source {src!r} (expected one of "
                f"{list(THREAD_LINT_SOURCES)})")
        n = rec.get("n_findings")
        fs = rec.get("findings")
        if n is not None and (not isinstance(n, int) or n < 0):
            problems.append(f"'n_findings' not a non-negative int: {n!r}")
        if fs is not None:
            if not isinstance(fs, list):
                problems.append("'findings' not a list")
            else:
                if isinstance(n, int) and n != len(fs):
                    problems.append(
                        f"n_findings {n} but {len(fs)} findings listed "
                        "— the count and the list disagree")
                for j, f in enumerate(fs):
                    if not isinstance(f, dict):
                        problems.append(f"finding {j} not a dict")
                        continue
                    if f.get("rule") not in THREAD_LINT_RULES:
                        problems.append(
                            f"finding {j} rule {f.get('rule')!r} not in "
                            f"the TH vocabulary "
                            f"{list(THREAD_LINT_RULES)}")
                    if not str(f.get("message", "")).strip():
                        problems.append(
                            f"finding {j} carries no message — a "
                            "finding the ledger cannot explain")
        ne = rec.get("n_edges")
        es = rec.get("edges")
        if ne is not None and (not isinstance(ne, int) or ne < 0):
            problems.append(f"'n_edges' not a non-negative int: {ne!r}")
        if es is not None:
            if not isinstance(es, list):
                problems.append("'edges' not a list")
            else:
                if isinstance(ne, int) and ne != len(es):
                    problems.append(
                        f"n_edges {ne} but {len(es)} edges listed — "
                        "the count and the list disagree")
                for j, e in enumerate(es):
                    if (not isinstance(e, list) or len(e) != 3
                            or not isinstance(e[0], str)
                            or not isinstance(e[1], str)):
                        problems.append(
                            f"edge {j} not a [held, acquired, "
                            f"site-or-count] triple: {e!r}")
        locks = rec.get("locks")
        if locks is not None:
            if not isinstance(locks, list):
                problems.append("'locks' not a list")
            else:
                for j, row in enumerate(locks):
                    if not isinstance(row, dict) or \
                            not str(row.get("name", "")).strip():
                        problems.append(f"lock row {j} names no lock")
                    elif not isinstance(row.get("acquires"), int):
                        problems.append(
                            f"lock row {j} ({row.get('name')}) carries "
                            "no integer 'acquires' count")
        return problems
    if kind == "kernelbench":
        for key in KERNELBENCH_RECORD_KEYS:
            if key not in rec:
                problems.append(f"kernelbench record missing '{key}'")
        if not str(rec.get("kernel", "")).strip():
            problems.append("kernelbench record names no kernel")
        for key in ("kernel_ms", "fallback_ms", "compile_ms",
                    "predicted_ms"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v != v or v < 0):
                problems.append(
                    f"'{key}' not a non-negative number: {v!r}")
        if rec.get("kernel_ms") is None and "error" not in rec:
            problems.append("kernelbench record with null kernel_ms "
                            "carries no 'error' note")
        for key in ("flops_frac", "bw_frac"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v != v or not 0.0 <= v <= 1.0):
                problems.append(
                    f"'{key}' not a roofline fraction in [0, 1]: {v!r}")
        v = rec.get("speedup")
        if v is not None and (not isinstance(v, (int, float))
                              or v != v or v <= 0):
            problems.append(f"'speedup' not a positive number: {v!r}")
        for key in ("flops", "bytes_accessed", "n_samples", "warmup"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, int) or v < 0):
                problems.append(
                    f"'{key}' not a non-negative int: {v!r}")
        b = rec.get("bound")
        if b is not None and b not in ("compute", "memory"):
            problems.append(f"'bound' not 'compute'/'memory': {b!r}")
        ev = rec.get("event")
        if ev is not None and ev not in KERNELBENCH_EVENTS:
            problems.append(f"unknown kernelbench event {ev!r} "
                            f"(expected one of "
                            f"{list(KERNELBENCH_EVENTS)})")
        return problems
    if kind == "commbench":
        for key in COMMBENCH_RECORD_KEYS:
            if key not in rec:
                problems.append(f"commbench record missing '{key}'")
        op = rec.get("op")
        if op is not None and op not in COMMBENCH_OPS:
            problems.append(f"unknown commbench op {op!r} (expected one "
                            f"of {list(COMMBENCH_OPS)})")
        for key in ("time_ms", "compile_ms", "predicted_ms", "db_ms",
                    "wire_bytes", "achieved_bw", "peak_bw"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v != v or v < 0):
                problems.append(
                    f"'{key}' not a non-negative number: {v!r}")
        if rec.get("time_ms") is None and "error" not in rec:
            problems.append("commbench record with null time_ms "
                            "carries no 'error' note")
        v = rec.get("bw_frac")
        if v is not None and (not isinstance(v, (int, float))
                              or v != v or not 0.0 <= v <= 1.0):
            problems.append(
                f"'bw_frac' not a bandwidth fraction in [0, 1]: {v!r}")
        for key in ("axis_size", "payload_bytes"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, int) or v < 1):
                problems.append(f"'{key}' not a positive int: {v!r}")
        for key in ("n_samples", "warmup"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, int) or v < 0):
                problems.append(
                    f"'{key}' not a non-negative int: {v!r}")
        m = rec.get("medium")
        if m is not None and m not in ("ici", "dcn"):
            problems.append(f"'medium' not 'ici'/'dcn': {m!r}")
        ev = rec.get("event")
        if ev is not None and ev not in COMMBENCH_EVENTS:
            problems.append(f"unknown commbench event {ev!r} "
                            f"(expected one of "
                            f"{list(COMMBENCH_EVENTS)})")
        return problems
    if kind == "plan":
        for key in PLAN_RECORD_KEYS:
            if key not in rec:
                problems.append(f"plan record missing '{key}'")
        chosen = rec.get("chosen")
        if chosen is not None:
            if not isinstance(chosen, dict):
                problems.append(f"'chosen' not a layout dict: {chosen!r}")
            else:
                for axis in ("dp", "pp", "mp"):
                    v = chosen.get(axis)
                    if not isinstance(v, int) or v < 1:
                        problems.append(
                            f"chosen layout '{axis}' not a positive "
                            f"int: {v!r}")
        n = rec.get("candidates_considered")
        rejected = rec.get("candidates_rejected")
        if n is not None and (not isinstance(n, int) or n < 1):
            problems.append(
                f"'candidates_considered' not a positive int: {n!r}")
        if rejected is not None:
            if not isinstance(rejected, list):
                problems.append("'candidates_rejected' not a list")
            else:
                if isinstance(n, int) and len(rejected) >= n:
                    problems.append(
                        f"{len(rejected)} rejected candidates but only "
                        f"{n} considered — the chosen layout cannot be "
                        "among them")
                for j, r in enumerate(rejected):
                    if not isinstance(r, dict) or \
                            not str(r.get("reason", "")).strip():
                        problems.append(
                            f"rejected candidate {j} carries no reason "
                            "— a rejection the ledger cannot explain")
        for key in ("projected_hbm_bytes", "measured_hbm_bytes",
                    "hbm_budget_bytes"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v < 0):
                problems.append(
                    f"'{key}' not a non-negative number: {v!r}")
        return problems
    if kind == "elastic":
        for key in ELASTIC_RECORD_KEYS:
            if key not in rec:
                problems.append(f"elastic record missing '{key}'")
        ev = rec.get("event")
        if ev is not None and ev not in ELASTIC_EVENTS:
            problems.append(f"unknown elastic event {ev!r} "
                            f"(expected one of {list(ELASTIC_EVENTS)})")
        if ev in ("heartbeat_miss", "declared_dead"):
            if not str(rec.get("host", "")).strip():
                problems.append(f"elastic {ev} record names no host")
            mc = rec.get("miss_count")
            if mc is not None and (not isinstance(mc, int) or mc < 1):
                problems.append(
                    f"'miss_count' not a positive int: {mc!r}")
        for key in ("world_from", "world_to"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, int) or v < 1):
                problems.append(f"'{key}' not a positive int: {v!r}")
        for key in ("layout_from", "layout_to"):
            v = rec.get(key)
            if v is None:
                continue
            if not isinstance(v, dict) or not v:
                problems.append(f"'{key}' not a non-empty layout "
                                f"dict: {v!r}")
            else:
                for a, s in v.items():
                    if not isinstance(s, int) or s < 1:
                        problems.append(
                            f"'{key}' axis {a!r} not a positive "
                            f"int: {s!r}")
        if ev == "reshard_restore":
            # the one event that must be fully anchored on its own:
            # which committed step moved, from which layout, to which
            if not isinstance(rec.get("step"), int):
                problems.append(
                    "elastic reshard_restore record references no step")
            for key in ("layout_from", "layout_to"):
                if not rec.get(key):
                    problems.append(
                        f"elastic reshard_restore record carries no "
                        f"'{key}'")
        v = rec.get("detect_s")
        if v is not None and (not isinstance(v, (int, float)) or v < 0):
            problems.append(f"'detect_s' not a non-negative number: {v!r}")
        return problems
    if kind == "serving":
        for key in SERVING_RECORD_KEYS:
            if key not in rec:
                problems.append(f"serving record missing '{key}'")
        ev = rec.get("event")
        if ev is not None and ev not in SERVING_EVENTS:
            problems.append(f"unknown serving event {ev!r} "
                            f"(expected one of {list(SERVING_EVENTS)})")
        for key in ("queue_depth", "queue_wait_ms", "queue_deadline_ms",
                    "predicted_wait_ms", "retry_after_s", "n_tokens",
                    "kv_blocks_used", "drained_ms",
                    "prefix_blocks_shared", "prefix_hit_rate",
                    "prefill_tokens_saved", "prefill_tokens_offered"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v != v or v < 0):
                problems.append(
                    f"'{key}' not a non-negative number: {v!r}")
        if ev == "quiesce":
            # quiesce must be auditable on its own: the accounting
            # snapshot and the pool state are WHAT it asserts
            if "kv_blocks_used" not in rec:
                problems.append(
                    "serving quiesce record carries no kv_blocks_used")
            counts = rec.get("counts")
            if not isinstance(counts, dict):
                problems.append(
                    "serving quiesce record carries no counts dict")
            else:
                for k, v in counts.items():
                    if not isinstance(v, int) or v < 0:
                        problems.append(
                            f"quiesce count {k!r} not a non-negative "
                            f"int: {v!r}")
        return problems
    if kind == "fleet":
        for key in FLEET_RECORD_KEYS:
            if key not in rec:
                problems.append(f"fleet record missing '{key}'")
        ev = rec.get("event")
        if ev is not None and ev not in FLEET_EVENTS:
            problems.append(f"unknown fleet event {ev!r} "
                            f"(expected one of {list(FLEET_EVENTS)})")
        if ev in ("route", "probe", "declared_dead", "failover",
                  "replay_spliced", "restart"):
            if not str(rec.get("replica", "")).strip():
                problems.append(f"fleet {ev} record names no replica")
        if ev == "declared_dead":
            mc = rec.get("miss_count")
            if not isinstance(mc, int) or mc < 1:
                problems.append(
                    f"fleet declared_dead 'miss_count' not a positive "
                    f"int: {mc!r}")
        if ev == "failover" and not str(rec.get("to_replica",
                                                "")).strip():
            problems.append("fleet failover record names no to_replica "
                            "— where did the request go?")
        if ev == "replay_spliced":
            # the splice must be auditable on its own: both halves and
            # the total are WHAT it asserts (the cross-rule checks the
            # arithmetic; the validator checks the fields exist)
            for key in ("streamed_before", "streamed_after", "n_tokens"):
                v = rec.get(key)
                if not isinstance(v, int) or v < 0:
                    problems.append(
                        f"fleet replay_spliced '{key}' not a "
                        f"non-negative int: {v!r}")
        if ev == "quiesce":
            counts = rec.get("counts")
            if not isinstance(counts, dict):
                problems.append(
                    "fleet quiesce record carries no counts dict")
            else:
                for k, v in counts.items():
                    if not isinstance(v, int) or v < 0:
                        problems.append(
                            f"fleet quiesce count {k!r} not a "
                            f"non-negative int: {v!r}")
        for key in ("miss_count", "detect_s", "streamed_before",
                    "streamed_after", "n_tokens", "queue_depth",
                    "retry_after_s"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v != v or v < 0):
                problems.append(
                    f"'{key}' not a non-negative number: {v!r}")
        return problems
    if kind == "reqtrace":
        for key in REQTRACE_RECORD_KEYS:
            if key not in rec:
                problems.append(f"reqtrace record missing '{key}'")
        outcome = rec.get("outcome")
        if outcome is not None and outcome not in REQTRACE_OUTCOMES:
            problems.append(f"unknown reqtrace outcome {outcome!r} "
                            f"(expected one of {list(REQTRACE_OUTCOMES)})")
        for key in ("e2e_ms", "t0_s", "ttft_ms", "tpot_ms",
                    "queue_wait_ms", "n_tokens", "prompt_len",
                    "preemptions"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v != v or v < 0):
                problems.append(
                    f"'{key}' not a non-negative number: {v!r}")
        spans = rec.get("spans")
        if spans is not None:
            if not isinstance(spans, list) or not spans:
                problems.append("'spans' not a non-empty list — a trace "
                                "with no timeline explains nothing")
            else:
                for j, sp in enumerate(spans):
                    if not isinstance(sp, dict):
                        problems.append(f"span {j} not a dict")
                        continue
                    if sp.get("kind") not in REQTRACE_SPAN_KINDS:
                        problems.append(
                            f"span {j} kind {sp.get('kind')!r} not in "
                            f"the vocabulary {list(REQTRACE_SPAN_KINDS)}")
                    for key in ("t0_ms", "dur_ms"):
                        v = sp.get(key)
                        if not isinstance(v, (int, float)) or v != v \
                                or v < 0:
                            problems.append(
                                f"span {j} '{key}' not a non-negative "
                                f"number: {v!r}")
        return problems
    if kind == "ckpt":
        for key in CKPT_RECORD_KEYS:
            if key not in rec:
                problems.append(f"ckpt record missing '{key}'")
        ev = rec.get("event")
        if ev is not None and ev not in CKPT_EVENTS:
            problems.append(f"unknown ckpt event {ev!r} "
                            f"(expected one of {list(CKPT_EVENTS)})")
        for key in ("save_ms", "bytes"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float)) or v < 0):
                problems.append(f"'{key}' not a non-negative number: {v!r}")
        if ev == "commit" and "save_ms" not in rec:
            problems.append("ckpt commit record carries no save_ms")
        return problems
    if kind == "memsnap":
        for key in MEMSNAP_RECORD_KEYS:
            if key not in rec:
                problems.append(f"memsnap record missing '{key}'")
        ev = rec.get("event")
        if ev is not None and ev not in MEMSNAP_EVENTS:
            problems.append(f"unknown memsnap event {ev!r} "
                            f"(expected one of {list(MEMSNAP_EVENTS)})")
        for key in ("total_bytes",) + MEMSNAP_BUCKETS + (
                "hbm_budget_bytes", "headroom_bytes", "projected_bytes",
                "kv_eviction_rate", "kv_admission_rate"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v != v or v < 0):
                problems.append(
                    f"'{key}' not a non-negative number: {v!r}")
        if rec.get("total_bytes") is None and "error" not in rec:
            problems.append("memsnap record with null total_bytes "
                            "carries no 'error' note")
        for key in ("kv_occupancy", "kv_cache_share"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v != v or not 0.0 <= v <= 1.0):
                problems.append(
                    f"'{key}' not a fraction in [0, 1]: {v!r}")
        for key in ("n_arrays", "kv_blocks_total", "kv_blocks_held",
                    "kv_blocks_free", "kv_blocks_cached",
                    "kv_evictions", "kv_admissions"):
            v = rec.get(key)
            if v is not None and (not isinstance(v, int) or v < 0):
                problems.append(
                    f"'{key}' not a non-negative int: {v!r}")
        for key in ("evictions_by_class", "admissions_by_class"):
            v = rec.get(key)
            if v is None:
                continue
            if not isinstance(v, dict):
                problems.append(f"'{key}' not a dict: {v!r}")
            else:
                for cls, n in v.items():
                    if not isinstance(n, int) or n < 0:
                        problems.append(
                            f"'{key}' count for class {cls!r} not a "
                            f"non-negative int: {n!r}")
        if ev == "postmortem":
            # the forensic contract: an OOM record that cannot say
            # what failed, or show WHO held the bytes, diagnoses
            # nothing offline
            if not str(rec.get("error", "")).strip():
                problems.append(
                    "memsnap postmortem carries no error note — a "
                    "forensic record that cannot say what killed the "
                    "allocation")
            ta = rec.get("top_arrays")
            if not isinstance(ta, list) or not ta:
                problems.append(
                    "memsnap postmortem carries no top_arrays listing "
                    "— an OOM with no suspects named")
            else:
                for j, a in enumerate(ta):
                    if not isinstance(a, dict) or \
                            not isinstance(a.get("bytes"), int) or \
                            a["bytes"] < 0:
                        problems.append(
                            f"top_arrays[{j}] carries no non-negative "
                            "'bytes'")
        return problems
    for key in STEP_RECORD_KEYS:
        if key not in rec:
            problems.append(f"step record missing '{key}'")
    for key in ("step_ms", "compile_ms", "execute_ms"):
        v = rec.get(key)
        if v is not None and (not isinstance(v, (int, float)) or v < 0):
            problems.append(f"'{key}' not a non-negative number: {v!r}")
    for key in ("tokens_per_sec", "mfu", "loss"):
        v = rec.get(key)
        if v is not None and not isinstance(v, (int, float)):
            problems.append(f"'{key}' not numeric: {v!r}")
        if isinstance(v, float) and (v != v or v in (float("inf"),
                                                     float("-inf"))):
            problems.append(f"'{key}' non-finite: {v!r}")
    for key in HEALTH_KEYS:
        # numeric when present; non-finite is ALLOWED here — a NaN
        # grad_norm is the health taps reporting a poisoned step, and
        # the paired nan/inf counts stay machine-checkable integers
        v = rec.get(key)
        if v is not None and not isinstance(v, (int, float)):
            problems.append(f"'{key}' not numeric: {v!r}")
    for key in INPUT_KEYS:
        v = rec.get(key)
        if v is None:
            continue
        if not isinstance(v, (int, float)) or v != v or v < 0:
            problems.append(
                f"'{key}' not a non-negative number: {v!r}")
        elif key == "input_bound_frac" and v > 1.0:
            problems.append(f"'input_bound_frac' above 1.0: {v!r}")
    for key in MOE_KEYS:
        v = rec.get(key)
        if v is None:
            continue
        if key == "moe_num_experts":
            if not isinstance(v, int) or v < 1:
                problems.append(
                    f"'moe_num_experts' not a positive int: {v!r}")
            continue
        if not isinstance(v, (int, float)) or v != v:
            problems.append(f"'{key}' not a finite number: {v!r}")
            continue
        if key in ("moe_entropy", "moe_dropped_frac", "moe_overflow") \
                and v < 0:
            problems.append(f"'{key}' negative: {v!r}")
        if key == "moe_dropped_frac" and v > 1.0:
            problems.append(f"'moe_dropped_frac' above 1.0: {v!r}")
    for key in COMM_KEYS:
        v = rec.get(key)
        if v is None:
            continue
        if not isinstance(v, (int, float)) or v != v or v < 0:
            problems.append(
                f"'{key}' not a non-negative number: {v!r}")
        elif key == "comm_frac" and v > 1.0:
            problems.append(f"'comm_frac' above 1.0: {v!r}")
    return problems


# ---------------------------------------------------------------------------
# Chrome trace export (CrossStackProfiler analog, multi-rank)
# ---------------------------------------------------------------------------

def spans_to_trace_events(spans, default_rank=0):
    """spans: iterable of dicts {name, t0, dur, rank?, tid?, cat?} (seconds)
    -> chrome trace 'X' events in microseconds, pid == rank."""
    events = []
    ranks = set()
    for sp in spans:
        rank = int(sp.get("rank", default_rank))
        ranks.add(rank)
        ev = {
            "name": sp["name"], "ph": "X",
            "pid": rank, "tid": int(sp.get("tid", 0)),
            "ts": float(sp["t0"]) * 1e6, "dur": float(sp["dur"]) * 1e6,
            "cat": sp.get("cat", "host"),
        }
        if sp.get("args"):
            ev["args"] = sp["args"]
        events.append(ev)
    meta = [{"name": "process_name", "ph": "M", "pid": r,
             "args": {"name": f"rank {r}"}} for r in sorted(ranks)]
    return meta + events


def export_chrome_tracing(path, sources, align_on=None):
    """Write one Chrome-trace JSON merging host spans across ranks.

    `sources` is a list whose items are either TelemetryRecorder objects
    (their `.spans` and `.rank` are used) or plain span-dict lists. Each
    rank becomes its own trace pid so the merged timeline reads like the
    reference CrossStackProfiler output. `align_on`: optional span name
    whose start is declared t=0 per rank (the `__sync__`-marker recipe
    from tools/merge_profiles.py).

    Spans still OPEN at export time (a stuck collective, an aborted
    step) are closed at 'now' and tagged args={'open': True} rather
    than dropped — an export made from a crash handler must show what
    the program was inside, not pretend it was idle.

    Returns the number of spans written. Output loads in chrome://tracing
    or Perfetto.
    """
    all_spans = []
    for i, src in enumerate(sources):
        spans = getattr(src, "spans", src)
        open_fn = getattr(src, "open_span_dicts", None)
        if open_fn is not None:
            spans = list(spans) + list(open_fn())
        rank = getattr(src, "rank", None)
        for sp in spans:
            sp = dict(sp)
            if "rank" not in sp:
                sp["rank"] = i if rank is None else rank
            all_spans.append(sp)
    if align_on is not None:
        zero = {}
        for sp in all_spans:
            if sp["name"] == align_on:
                zero.setdefault(sp["rank"], sp["t0"])
        for sp in all_spans:
            sp["t0"] = sp["t0"] - zero.get(sp["rank"], 0.0)
    events = spans_to_trace_events(all_spans)
    path = os.fspath(path)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return len(all_spans)
