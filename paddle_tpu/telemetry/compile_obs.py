"""Compile observatory: recompile tracking with cause diffs, compiled-HBM
accounting, and cost-model cross-checks.

The flight recorder (recorder.py) measures what a step COST and the
health monitor (health.py) watches a job running WRONG; this module
watches the COMPILER — the third silent failure mode of a jit-and-trace
stack:

- a **retrace storm**: a shape/dtype/weak-type/static-arg thrash that
  recompiles the train step every few batches. The recorder shows
  nonzero compile_ms; only a signature DIFF says *why* ("arg `batch[0]`
  axis 0: 32→48"), and only a storm rule says it is pathological.
- an **HBM surprise**: the executable XLA actually built carries temp /
  generated-code buffers the static `analysis/sharding_lint.project_hbm`
  (SH206) projection never saw. `compiled.memory_analysis()` has the
  real number — computed on every compile, recorded nowhere, until now.
- **cost-model drift**: MFU claims divide measured time by an analytic
  FLOPs number (`telemetry/mfu.py`); when the compiled program's own
  cost analysis (`cost_model._safe_cost_analysis`) disagrees, every MFU
  in the run is quietly wrong.

Mechanics — three layers, same pattern as the rest of telemetry
(context-activated, zero call-site changes):

- **CompileSignature / diff_signatures** — per-leaf aval descriptors
  (name from the arg tree path, shape, dtype, weak_type, sharding) plus
  static values and the donate set; diffing two signatures yields the
  human-readable recompile causes.
- **CompileObservatory** — a context manager (module stack, like
  TelemetryRecorder). While active, `jit.TrainStep`,
  `distributed.ShardedTrainStep` and `PipelineParallel.train_batch`
  dispatch through `observatory.call(family, jitted, *args)`: an AOT
  `lower().compile()` cache keyed on the signature. A miss IS a
  (re)compile — measured under the clock, diffed against the family's
  prior signature, enriched with `memory_analysis()`, XLA cost
  analysis, and a top-K optimized-HLO opcode profile
  (`cost_model.profile_hlo_text`), written as one JSONL record
  (sink.make_compile_record) and judged by the PR-3 AnomalyDetector
  (recompile_storm / hbm_projection_drift / flops_drift). A hit
  dispatches the cached executable — steady-state overhead is building
  the signature (one Python pass over the arg leaves) plus a dict
  lookup; the observatory is an opt-in context, not an always-on tax.
- **jax.monitoring bridge** — compiles that happen OUTSIDE the wrapped
  steps (a stray `jax.jit` in the loss, eval graphs, bench phases)
  still surface: the event-duration listener records each
  backend_compile as an `untracked` compile record and advances
  `compile.unattributed`, so the JSONL accounts for every compile the
  process paid for, attributed or not.

Monitor surface (scraped by telemetry.metrics_http `/metrics`):
counters `compile.count`, `compile.recompiles`, `compile.storms`,
`compile.unattributed`, `compile.aot_hits`; gauges
`compile.hbm_total_bytes`, `compile.hbm_arg_bytes`,
`compile.hbm_temp_bytes`, `compile.hbm_out_bytes`,
`compile.hbm_code_bytes`, `compile.last_ms`, `compile.flops`.

Offline, `tools/compile_report.py` replays the same detector rules over
the JSONL and renders the report (causes timeline, HBM breakdown,
roofline, top-K ops); `tools/trace_check.py` validates the records.

Reference analogs: JAX's own compile-cache miss explanations
(`jax_explain_cache_misses`) and Xprof compile-time attribution;
MegaScale-style per-job compilation accounting.
"""
import contextlib
import hashlib
import time
import warnings

import jax

from .. import monitor
from .sink import make_compile_record

__all__ = ["CompileObservatory", "CompileSignature", "RecompileTracker",
           "current_observatory", "diff_signatures", "signature_of",
           "memory_analysis_dict"]

_OBS_STACK = []                 # active (context-entered) observatories
_LISTENER_INSTALLED = False

# only the backend compile event counts as "a compile" for the
# unattributed stream: the trace/MLIR events of the same miss would
# triple-count it (recorder.py sums all three for the compile_ms SPLIT;
# here each record must be one program)
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def current_observatory():
    """The innermost context-active CompileObservatory, or None."""
    return _OBS_STACK[-1] if _OBS_STACK else None


def dispatch(family, jitted, args, arg_names=None, static=None,
             donate=None):
    """The one-line train-step integration point: route `jitted(*args)`
    through the active observatory's recorded AOT cache, or call it
    plainly (one stack peek) when none is active. All four wired
    dispatch sites (TrainStep, ShardedTrainStep, both
    PipelineParallel.train_batch branches) go through here, so the
    observatory contract has a single place to change."""
    obs = current_observatory()
    if obs is None:
        return jitted(*args)
    return obs.call(family, jitted, *args, arg_names=arg_names,
                    static=static, donate=donate)


def _jax_compile_listener(event, duration, **kwargs):
    if event != _BACKEND_COMPILE_EVENT:
        return
    obs = current_observatory()
    if obs is not None:
        obs._on_jax_compile_event(duration)


def _install_listener():
    """Idempotently hook jax's compile-event stream (stays registered
    for the process lifetime; a no-op while no observatory is active)."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    jax.monitoring.register_event_duration_secs_listener(
        _jax_compile_listener)
    _LISTENER_INSTALLED = True


# ---------------------------------------------------------------------------
# signatures + cause diffs
# ---------------------------------------------------------------------------

def _leaf_desc(x):
    """(shape, dtype, weak_type, sharding) of one argument leaf."""
    try:
        from jax.api_util import shaped_abstractify
        aval = shaped_abstractify(x)
        shape = tuple(aval.shape)
        dtype = str(aval.dtype)
        weak = bool(getattr(aval, "weak_type", False))
    except Exception:
        shape = tuple(getattr(x, "shape", ()))
        dtype = str(getattr(x, "dtype", type(x).__name__))
        weak = False
    sh = getattr(x, "sharding", None)
    return shape, dtype, weak, (str(sh) if sh is not None else None)


class CompileSignature:
    """What a jit cache key is MADE OF, kept human-addressable: one
    descriptor per argument leaf (name derived from the arg tree path,
    e.g. `batch[0]` or `opt_states[1]['m']`), the static values the
    caller declares, and the donate set. Equality of `.key` means the
    jit cache would hit; a changed key plus `diff_signatures` names the
    recompile cause."""

    def __init__(self, leaves, static=None, donate=None):
        self.leaves = tuple(leaves)          # [(name, shape, dtype, wt, sh)]
        self.static = dict(static or {})
        self.donate = tuple(donate or ())
        self.key = (self.leaves,
                    tuple(sorted((k, repr(v))
                                 for k, v in self.static.items())),
                    self.donate)

    def summary(self):
        """Compact JSONL form (the full leaf list would bloat every
        record; the diff is precomputed into `cause` instead). The
        digest is a stable content hash — NOT Python hash(), which is
        per-process randomized — so identical programs digest equal
        across ranks and runs (multi-rank merge / replay correlation)."""
        digest = hashlib.sha1(repr(self.key).encode()).hexdigest()[:8]
        return {"n_leaves": len(self.leaves), "digest": digest}

    def __eq__(self, other):
        return isinstance(other, CompileSignature) and self.key == other.key

    def __hash__(self):
        return hash(self.key)

    def __repr__(self):
        return (f"CompileSignature({len(self.leaves)} leaves, "
                f"static={self.static}, donate={self.donate})")


def signature_of(args, arg_names=None, static=None, donate=None):
    """Build the signature of a positional-args tuple. `arg_names` (one
    per top-level arg) roots the leaf paths — causes then read
    "arg `batch[0]` ..." instead of "arg `[5][0]` ..."."""
    from jax.tree_util import keystr, tree_flatten_with_path
    leaves = []
    for i, arg in enumerate(args):
        root = arg_names[i] if arg_names and i < len(arg_names) else f"[{i}]"
        paths, _ = tree_flatten_with_path(arg)
        for path, leaf in paths:
            leaves.append((root + keystr(path), *_leaf_desc(leaf)))
    return CompileSignature(leaves, static=static, donate=donate)


def _shape_cause(name, old_shape, new_shape):
    if len(old_shape) == len(new_shape):
        changed = [i for i, (a, b) in enumerate(zip(old_shape, new_shape))
                   if a != b]
        axes = ", ".join(f"axis {i}: {old_shape[i]}→{new_shape[i]}"
                         for i in changed)
        return (f"arg `{name}` {axes} "
                f"(shape {old_shape}→{new_shape})")
    return (f"arg `{name}` rank {len(old_shape)}→{len(new_shape)} "
            f"(shape {old_shape}→{new_shape})")


def diff_signatures(old, new):
    """Human-readable causes for why `new` missed where `old` compiled.
    Returns a list of strings, one per changed facet; empty only when
    the signatures are equal (a recompile with an empty diff means the
    jit key involves something the signature cannot see — reported as
    such rather than silently)."""
    if old is None:
        return []
    causes = []
    olds = {name: rest for name, *rest in old.leaves}
    news = {name: rest for name, *rest in new.leaves}
    added = [n for n in news if n not in olds]
    removed = [n for n in olds if n not in news]
    if added or removed:
        causes.append(
            f"arg set changed: {len(old.leaves)}→{len(new.leaves)} "
            f"leaves"
            + (f", added {added[:4]}" if added else "")
            + (f", removed {removed[:4]}" if removed else ""))
    for name in news:
        if name not in olds:
            continue
        (oshape, odt, owt, osh) = olds[name]
        (nshape, ndt, nwt, nsh) = news[name]
        if oshape != nshape:
            causes.append(_shape_cause(name, oshape, nshape))
        if odt != ndt:
            causes.append(f"arg `{name}` dtype {odt}→{ndt}")
        if owt != nwt:
            causes.append(f"weak_type flip on `{name}` ({owt}→{nwt})")
        if osh != nsh and oshape == nshape:
            causes.append(f"arg `{name}` sharding {osh}→{nsh}")
    for k in sorted(set(old.static) | set(new.static)):
        ov, nv = old.static.get(k), new.static.get(k)
        if repr(ov) != repr(nv):
            causes.append(f"static `{k}` {ov!r}→{nv!r}")
    if old.donate != new.donate:
        causes.append(f"new donate set {old.donate}→{new.donate}")
    if not causes:
        causes.append("signature unchanged (cache miss from outside the "
                      "observed facets — e.g. a fresh jit object)")
    return causes


# ---------------------------------------------------------------------------
# compiled-executable introspection
# ---------------------------------------------------------------------------

def memory_analysis_dict(compiled):
    """`compiled.memory_analysis()` flattened to plain per-device byte
    counts ({arg,out,temp,code,alias,total}_bytes), None when the
    backend refuses (the same degrade stance as _safe_cost_analysis).
    total excludes generated code: it is the HBM the program's DATA
    needs, the number SH206 projects."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        d = {
            "arg_bytes": int(ma.argument_size_in_bytes),
            "out_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "code_bytes": int(ma.generated_code_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
        }
        # aliased (donated) buffers are counted in arg_bytes but their
        # output side is not a second allocation
        d["total_bytes"] = (d["arg_bytes"] + d["out_bytes"]
                            + d["temp_bytes"] - d["alias_bytes"])
        return d
    except Exception:
        return None


def _cost_dict(compiled):
    from ..cost_model import _safe_cost_analysis
    ca = _safe_cost_analysis(compiled)
    flops = float(ca.get("flops", 0.0) or 0.0)
    byts = float(ca.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0 and byts <= 0:
        return None
    return {"flops": flops, "bytes_accessed": byts}


def _hlo_ops(compiled, top_k):
    try:
        from ..cost_model import profile_hlo_text
        prof = profile_hlo_text(compiled.as_text(), top_k=top_k)
        return prof["by_op"] or None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# the tracker (record-keeping half — usable offline/standalone)
# ---------------------------------------------------------------------------

class RecompileTracker:
    """Per-family compile ledger: remembers each family's last
    signature, assigns the per-family ordinal (n_compiles), produces
    the cause diff, and builds the JSONL record. Pure bookkeeping — the
    observatory owns dispatch, counters and judgment, so this half is
    reusable anywhere a compile is observed (StepTimer, tests)."""

    def __init__(self, rank=0, backend=None):
        self.rank = int(rank)
        self.backend = backend
        self.families = {}           # family -> (last signature, count)
        self._last_step = {}         # family -> last recorded step
        self.records = []

    def observe(self, family, signature, compile_ms, step, hbm=None,
                cost=None, hlo_ops=None, hbm_projected_bytes=None,
                analytic_flops=None, untracked=False):
        """Account one compile; returns the record dict (kind='compile').

        The step clock is clamped non-decreasing PER FAMILY: sources
        with instance-local clocks (a fresh StepTimer restarting at 0
        under a family name an earlier instance used) must not make the
        ledger run backwards — trace_check validates monotonicity."""
        step = max(int(step), self._last_step.get(family, 0))
        self._last_step[family] = step
        prev, count = self.families.get(family, (None, 0))
        cause = diff_signatures(prev, signature) \
            if signature is not None else None
        if signature is not None:
            self.families[family] = (signature, count + 1)
        else:
            self.families[family] = (prev, count + 1)
        backend = self.backend
        if backend is None:
            try:
                backend = jax.default_backend()
            except Exception:
                backend = None
        rec = make_compile_record(
            fn=family, step=step, compile_ms=compile_ms, rank=self.rank,
            n_compiles=count + 1, backend=backend,
            cause=cause or None,
            signature=signature.summary() if signature is not None else None,
            hbm=hbm, cost=cost, hlo_ops=hlo_ops,
            hbm_projected_bytes=hbm_projected_bytes,
            analytic_flops=analytic_flops, untracked=untracked)
        self.records.append(rec)
        return rec


# ---------------------------------------------------------------------------
# the observatory
# ---------------------------------------------------------------------------

class CompileObservatory:
    """Context-active compile watcher + AOT dispatch cache.

    obs = CompileObservatory(sink="run.jsonl",
                             hbm_projection=report,      # project_hbm()
                             analytic_flops=fpt * B * S) # MFU's number
    with rec, obs:                      # recorder optional but natural
        for batch in loader:
            loss = train_step(*batch)   # steps dispatch THROUGH obs

    hbm_projection: int bytes or the report dict `project_hbm` returns
    (its per_device.total_bytes is used) — every compile record then
    carries the projection and the detector cross-checks >15% drift.
    analytic_flops: the per-step analytic FLOPs MFU accounting uses —
    compiled cost-analysis FLOPs are cross-checked against it.
    health: an existing HealthMonitor to route anomalies through
    (shares its action/counters/ring); None uses `action` directly
    ('warn' default, 'record', 'raise' HealthError).
    """

    def __init__(self, sink=None, rank=0, health=None, action="warn",
                 config=None, hbm_projection=None, analytic_flops=None,
                 hlo_top_k=8, track_hlo=True, aot_cache_size=32):
        import collections
        from .health import AnomalyDetector, HealthConfig
        from .sink import JsonlSink
        self._owns_sink = isinstance(sink, str)
        self.sink = JsonlSink(sink) if self._owns_sink else sink
        self.rank = int(rank)
        self.health = health
        if isinstance(config, dict):
            config = HealthConfig(**config)
        self.config = config or (health.config if health is not None
                                 else HealthConfig(action=action))
        self.detector = (health.detector if health is not None
                         else AnomalyDetector(self.config))
        self.tracker = RecompileTracker(rank=rank)
        self.analytic_flops = analytic_flops
        self.hbm_projection = self._projection_bytes(hbm_projection)
        self.hlo_top_k = int(hlo_top_k)
        self.track_hlo = bool(track_hlo)
        # bounded LRU: during the exact pathology this tool diagnoses
        # (a signature thrash) an unbounded cache would pin every stale
        # executable — and its jitted object — for the process lifetime
        self._aot = collections.OrderedDict()   # key -> (jitted, compiled)
        self._aot_cap = int(aot_cache_size)
        self._calls = 0
        self._compiling = 0           # listener suppression depth
        _install_listener()

    @staticmethod
    def _projection_bytes(proj):
        if proj is None:
            return None
        if isinstance(proj, dict):
            per_dev = proj.get("per_device", proj)
            return int(per_dev.get("total_bytes"))
        return int(proj)

    # -- context activation -------------------------------------------------
    def __enter__(self):
        _OBS_STACK.append(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        _OBS_STACK.remove(self)
        if self.sink is not None:
            if self._owns_sink:
                self.sink.close()
            elif hasattr(self.sink, "flush"):
                self.sink.flush()
        return False

    # -- dispatch path ------------------------------------------------------
    def call(self, family, jitted, *args, arg_names=None, static=None,
             donate=None):
        """Dispatch `jitted(*args)` through the observatory: an AOT
        cache keyed on the args' signature AND the jitted object's
        identity (a rebuilt jit is a new program even when the
        signature cannot see why — e.g. a trainer re-jitting for a new
        optimizer). A miss lowers+compiles under the clock and
        records/judges the compile; a hit calls the cached executable.
        The jit's own params (shardings, donation) ride through
        lower(), so the executed program is the one the plain dispatch
        would have built."""
        sig = signature_of(args, arg_names=arg_names, static=static,
                           donate=donate)
        key = (family, id(jitted), sig.key)
        entry = self._aot.get(key)
        if entry is None:
            with self.compiling():
                t0 = time.perf_counter()
                compiled = jitted.lower(*args).compile()
                compile_ms = (time.perf_counter() - t0) * 1000.0
            # the entry pins the jitted object so its id() cannot be
            # recycled while the cache would still answer for it;
            # past the cap the least-recently-used executable goes (a
            # re-use after eviction re-lowers and is recorded again)
            self._aot[key] = (jitted, compiled)
            while len(self._aot) > self._aot_cap:
                self._aot.popitem(last=False)
            self.observe(family, sig, compile_ms, compiled=compiled,
                         cross_check=True)
        else:
            self._aot.move_to_end(key)
            compiled = entry[1]
            monitor.incr("compile.aot_hits")
        self._calls += 1
        return compiled(*args)

    @contextlib.contextmanager
    def compiling(self):
        """Suppress the jax.monitoring bridge for a compile this
        observatory is about to attribute itself (also used by
        StepTimer around its own lower/compile)."""
        self._compiling += 1
        try:
            yield
        finally:
            self._compiling -= 1

    # -- observation (also the StepTimer entry point) -----------------------
    def observe(self, family, signature, compile_ms, compiled=None,
                hbm=None, cost=None, untracked=False, step=None,
                cross_check=False):
        """Account one compile: enrich (memory/cost/HLO from the
        compiled executable when given), record, gauge, judge.

        cross_check: attach the observatory's hbm_projection /
        analytic_flops to this record (and so run the drift rules).
        Only the wrapped TRAIN-STEP dispatch sets it — those are the
        programs the projection/analytic numbers describe; a StepTimer
        helper or stray jit must not be judged against them.
        step: explicit step clock for the record (StepTimer passes its
        call count); defaults to the active recorder's step index, else
        this observatory's dispatch count."""
        hlo_ops = None
        if compiled is not None:
            if hbm is None:
                hbm = memory_analysis_dict(compiled)
            if cost is None:
                cost = _cost_dict(compiled)
            if self.track_hlo:
                hlo_ops = _hlo_ops(compiled, self.hlo_top_k)
        rec = self.tracker.observe(
            family, signature, compile_ms,
            step=self._current_step() if step is None else int(step),
            hbm=hbm, cost=cost, hlo_ops=hlo_ops,
            hbm_projected_bytes=(self.hbm_projection
                                 if hbm and cross_check else None),
            analytic_flops=(self.analytic_flops
                            if cost and cross_check else None),
            untracked=untracked)

        monitor.incr("compile.count")
        if untracked:
            monitor.incr("compile.unattributed")
        elif rec["n_compiles"] > 1:
            monitor.incr("compile.recompiles")
        monitor.set_gauge("compile.last_ms", rec["compile_ms"])
        if hbm:
            for k in ("total", "arg", "temp", "out", "code"):
                v = hbm.get(f"{k}_bytes")
                if v is not None:
                    monitor.set_gauge(f"compile.hbm_{k}_bytes", float(v))
        if cost:
            monitor.set_gauge("compile.flops", cost["flops"])

        if self.sink is not None:
            self.sink.write(rec)
        found = self.detector.observe(rec)
        if found:
            self._act(found)
        return rec

    # -- internals ----------------------------------------------------------
    def _current_step(self):
        from .recorder import current_recorder
        rec = current_recorder()
        if rec is not None:
            return rec._step_idx
        return self._calls

    def _on_jax_compile_event(self, duration):
        if self._compiling > 0:
            return        # an attributed compile is mid-flight on some
            # thread; its own observe() accounts it. (Cross-thread races
            # would at worst mis-file one event as attributed.)
        self.observe("(jax)", None, duration * 1000.0, untracked=True)

    def _act(self, anomalies):
        from .health import HealthError
        storms = sum(1 for a in anomalies if a.kind == "recompile_storm")
        if storms:
            monitor.incr("compile.storms", storms)
        if self.health is not None:
            # shared monitor: its action/counters own the response
            self.health._act(anomalies)
            return
        monitor.incr("health.anomalies", len(anomalies))
        if self.config.action == "record":
            return
        if self.config.action == "warn":
            for a in anomalies:
                warnings.warn(f"[compile] {a.message}", RuntimeWarning,
                              stacklevel=4)
            return
        raise HealthError(anomalies)

    @property
    def anomalies(self):
        return self.detector.anomalies

    @property
    def records(self):
        return self.tracker.records
