"""Live scrape surface for a training job: /healthz, /metrics, /steps.

Stdlib-only (http.server) so a production run carries no serving
dependency: point Prometheus (or curl) at the port and a silent job
becomes inspectable without touching its stdout or attaching anything.

- **GET /metrics** — Prometheus text exposition (0.0.4): every
  `paddle_tpu.monitor` counter as a monotonic `counter`, every gauge
  (including the health taps' last-seen grad_norm/update_ratio and
  process uptime/rank) as a `gauge`, plus the last step record's
  numeric fields as `paddle_tpu_last_step_*` gauges when a recorder or
  health monitor is attached.
- **GET /healthz** — one JSON object: status ("ok" | "stalled" |
  "anomalous"), uptime, steps, anomaly/nan counters, watchdog state.
  Status "stalled" answers 503 so a dumb HTTP prober doubles as a hang
  alarm.
- **GET /steps[?n=50]** — JSON tail of the most recent step records
  (the health ring buffer, else the recorder's records list).

Bind is loopback by default; pass host="0.0.0.0" deliberately for a
pod-visible scrape. port=0 picks a free port (tests, multi-job hosts).

    srv = MetricsServer(recorder=rec, health=mon, port=9464).start()
    ... train ...
    srv.stop()
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .. import monitor

__all__ = ["MetricsServer", "prometheus_text"]

_PREFIX = "paddle_tpu_"


def _prom_name(name):
    out = []
    for ch in str(name):
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    sanitized = "".join(out)
    if sanitized and sanitized[0].isdigit():
        sanitized = "_" + sanitized
    return _PREFIX + sanitized


def _prom_value(v):
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if not float(f).is_integer() else str(int(f))


def _prom_le(bound):
    """le-label formatting: integral bounds print bare, others compact."""
    f = float(bound)
    return str(int(f)) if f.is_integer() else f"{f:g}"


def prometheus_text(last_record=None):
    """Render monitor.snapshot_typed() (+ optionally the last step
    record) as Prometheus exposition text. Counters keep their
    monotonic `# TYPE` so rate() works on the scrape; histograms
    (monitor.observe_hist, e.g. the serving latency distributions)
    render as true `histogram` series — cumulative `le` buckets + _sum
    + _count — so quantiles are computable AT SCRAPE TIME over any
    window, instead of trusting a producer-side percentile gauge that
    freezes whenever the producer stalls."""
    typed = monitor.snapshot_typed()
    lines = []
    for kind in ("counter", "gauge"):
        for name in sorted(typed[kind]):
            val = _prom_value(typed[kind][name])
            if val is None:
                continue
            pname = _prom_name(name)
            lines.append(f"# TYPE {pname} {kind}")
            lines.append(f"{pname} {val}")
    hists = monitor.snapshot_hists()
    for name in sorted(hists):
        h = hists[name]
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} histogram")
        cum = 0
        for bound, count in zip(h["bounds"], h["counts"]):
            cum += count
            lines.append(
                f'{pname}_bucket{{le="{_prom_le(bound)}"}} {cum}')
        lines.append(f'{pname}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{pname}_sum {_prom_value(h['sum'])}")
        lines.append(f"{pname}_count {h['count']}")
    if last_record:
        for key in sorted(last_record):
            v = last_record[key]
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            val = _prom_value(v)
            if val is None:
                continue
            pname = _prom_name(f"last_step_{key}")
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {val}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-health/1"

    # the ThreadingHTTPServer instance carries .metrics (MetricsServer)
    def _send(self, code, body, ctype="application/json"):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        ms = self.server.metrics
        url = urlparse(self.path)
        if url.path in ("/", "/healthz"):
            status, body = ms.healthz()
            self._send(503 if body["status"] == "stalled" else 200,
                       json.dumps(body, indent=2, default=repr))
        elif url.path == "/metrics":
            self._send(200, prometheus_text(ms.last_record()),
                       ctype="text/plain; version=0.0.4; charset=utf-8")
        elif url.path == "/steps":
            q = parse_qs(url.query)
            try:
                n = int(q.get("n", ["50"])[0])
            except ValueError:
                n = 50
            self._send(200, json.dumps(ms.steps_tail(n), default=repr))
        else:
            self._send(404, json.dumps(
                {"error": f"unknown path {url.path!r}",
                 "endpoints": ["/healthz", "/metrics", "/steps?n=50"]}))

    def log_message(self, fmt, *args):   # silence per-request stderr spam
        pass


class MetricsServer:
    """Threaded HTTP scrape endpoint over the process's monitor
    registry, an optional TelemetryRecorder, and an optional
    HealthMonitor. start() is non-blocking (daemon serve thread)."""

    def __init__(self, recorder=None, health=None, host="127.0.0.1",
                 port=0):
        self.recorder = recorder
        self.health = health
        self.host = host
        self.port = int(port)
        self._httpd = None
        self._thread = None

    # -- data plumbing ------------------------------------------------------
    def steps_tail(self, n=50):
        n = max(1, min(int(n), 10000))
        if self.health is not None and len(self.health.ring):
            return list(self.health.ring)[-n:]
        if self.recorder is not None:
            return list(self.recorder.records[-n:])
        return []

    def last_record(self):
        tail = self.steps_tail(1)
        return tail[-1] if tail else None

    def healthz(self):
        snap = monitor.snapshot()
        body = {
            "status": "ok",
            "uptime_s": snap.get("process.uptime_s"),
            "rank": snap.get("process.rank"),
            "steps": snap.get("telemetry.steps", 0),
            "train_steps": snap.get("jit.train_steps", 0),
            "anomalies": snap.get("health.anomalies", 0),
            "nan_steps": snap.get("health.nan_steps", 0),
            "watchdog_fires": snap.get("health.watchdog_fires", 0),
            # compile observatory (telemetry.compile_obs): a probe can
            # spot a retrace storm without parsing the JSONL
            "compiles": snap.get("compile.count", 0),
            "recompiles": snap.get("compile.recompiles", 0),
            "compile_storms": snap.get("compile.storms", 0),
            # resilience runtime (paddle_tpu.resilience): is this job
            # actually checkpointing, and has it had to retry/fall back
            "checkpoint": {
                "saves": snap.get("ckpt.saves", 0),
                "commits": snap.get("ckpt.commits", 0),
                "restores": snap.get("ckpt.restores", 0),
                "fallbacks": snap.get("ckpt.fallbacks", 0),
                "failures": snap.get("ckpt.failures", 0),
                "retries": snap.get("ckpt.retries", 0),
                "preemptions": snap.get("ckpt.preemptions", 0),
                "last_step": snap.get("ckpt.last_step"),
                "last_save_ms": snap.get("ckpt.save_ms"),
            },
            # serving resilience (paddle_tpu.serving): is the engine
            # shedding/expiring/cancelling under load, is it draining,
            # and has it had to warm-restart after step faults
            "serving": {
                "queue_depth": snap.get("serving.queue_depth"),
                "running": snap.get("serving.running"),
                "admitted": snap.get("serving.admitted", 0),
                "shed": snap.get("serving.shed", 0),
                "cancelled": snap.get("serving.cancelled", 0),
                "deadline_exceeded": snap.get(
                    "serving.deadline_exceeded", 0),
                "client_disconnects": snap.get(
                    "serving.client_disconnects", 0),
                "queue_wait_ms_p99": snap.get(
                    "serving.queue_wait_ms_p99"),
                "engine_errors": snap.get("serving.engine_errors", 0),
                "restarts": snap.get("serving.restarts", 0),
                "draining": snap.get("serving.draining", 0),
                "engine_dead": snap.get("serving.engine_dead", 0),
            },
            # elastic mesh resilience (distributed.elastic +
            # resilience.reshard): has the failure detector fired, and
            # did any resume cross a layout change
            "elastic": {
                "alive_hosts": snap.get("elastic.alive_hosts"),
                "heartbeat_misses": snap.get(
                    "elastic.heartbeat_miss", 0),
                "declared_dead": snap.get("elastic.declared_dead", 0),
                "replans": snap.get("elastic.replan", 0),
                "relaunches": snap.get("elastic.relaunch", 0),
                "reshard_restores": snap.get(
                    "elastic.reshard_restores", 0),
                "collective_timeouts": snap.get(
                    "elastic.collective_timeouts", 0),
            },
        }
        h = self.health
        if h is not None:
            body["anomaly_kinds"] = h.detector.kinds()
            wd = h.watchdog
            if wd is not None:
                overdue = wd.overdue_s()
                body["watchdog"] = {
                    "armed": wd.armed,
                    "deadline_s": wd.deadline_s,
                    "overdue_s": round(max(0.0, overdue), 3),
                    "dumps": list(wd.dumps),
                }
                if overdue > 0:
                    body["status"] = "stalled"
            if body["status"] == "ok" and h.anomalies:
                body["status"] = "anomalous"
        last = self.last_record()
        if last:
            body["last_step"] = last
        return 200, body

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        httpd.daemon_threads = True
        httpd.metrics = self
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="paddle-tpu-metrics-http",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
