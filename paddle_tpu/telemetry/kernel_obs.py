"""Kernel observatory: measured rooflines, a persistent timing DB, and
autotune-ready config search over the registered Pallas kernels.

The dynamic half of the kernel level. The Kernel Doctor
(analysis/kernel_lint.py, tools/kerneldoctor.py) proves every kernel in
`ops/kernel_registry.registered_kernels()` *statically* honest (KN501
races, KN502 VMEM, KN503 cost, KN504 parity, KN505 grid sanity); this
module *measures* them:

- **measure_kernel** — run a registration's seeded canonical example
  under warmup + median-of-k timing (`block_until_ready`; the program is
  AOT `lower().compile()`d first, the PR-4 compile-observatory
  discipline, so compile_ms is recorded separately and never pollutes
  the execute median), time the declared exact fallback on the same
  inputs, and report the kernel-vs-fallback speedup.
- **roofline** — combine measured time with the KN503 traced counts
  (`kernel_lint.count_body_cost` x grid steps for FLOPs,
  `kernel_lint.counted_dma_bytes` for the revisit-aware DMA stream) and
  the shared peak tables in `telemetry/mfu.py` (PEAK_FLOPS_BY_KIND +
  PEAK_HBM_BW_BY_KIND) into achieved-FLOP/s and achieved-bandwidth
  fractions, a compute- vs memory-bound verdict, and the
  roofline-predicted time the `kernel_time_drift` rule
  (telemetry/health.py) judges measured time against.
- **KernelDB** — tools/kernel_db.json: best-known timing + chosen
  config per (kernel, shape-signature, dtype, backend) key. Rolled
  forward only by `kernellab --update-db`, which refuses non-finite
  rows exactly like `bench_gate --update-baseline`.
- **tune_flash_fwd / tuned_blocks** — the config-search hook: enumerate
  the (block_q, block_k) candidate space (the absorbed
  tools/attn_tune.py sweep spec, ATTN_SWEEP_BQ x ATTN_SWEEP_BK) with
  `kernel_registry.vmem_footprint` (KN502) as the feasibility predicate
  and measured time as the objective; the winner is KN504
  parity-re-fuzzed (`kernel_lint.check_fallback_parity`) before it may
  be persisted. `ops/pallas_attention._resolve_blocks` and the
  decode/MoE block choices consult the DB through `tuned_blocks` /
  `tuned_param` ONLY when the opt-in env flag below is set, with the
  hand-tuned defaults as fallback.

Opt-in flag: set ``PADDLE_TPU_KERNEL_DB=/path/to/kernel_db.json`` (or
``=1`` for the checked-in tools/kernel_db.json) to let kernel call
sites resolve tuned configs from the DB. Unset (the default), the
measured hand-tuned policies apply and this module is never imported on
the hot path.

Every measurement is emitted as a typed ``kind=kernelbench`` record
(telemetry/sink.make_kernelbench_record, validated by
tools/trace_check.py) and mirrored as ``kernel.*`` gauges on /metrics.
CLI: tools/kernellab.py (--smoke / --selfcheck / --tune / --update-db).
"""
import functools
import json
import math
import os
import statistics
import time

import numpy as np

from .. import monitor
from .mfu import device_peak_flops, device_peak_hbm_bw
from .sink import make_kernelbench_record

__all__ = [
    "ATTN_SWEEP_BQ", "ATTN_SWEEP_BK", "DEFAULT_DB_PATH", "KernelDB",
    "MeasureResult", "db_flag_path", "db_key", "measure_kernel",
    "measure_registry", "roofline", "shape_signature", "traced_cost",
    "tune_flash_fwd", "tuned_blocks", "tuned_param",
]

# the flash-attention sweep space, absorbed verbatim from the round-5
# tools/attn_tune.py harness so the tuner and the historical sweeps can
# never drift (attn_tune imports these back)
ATTN_SWEEP_BQ = (256, 512, 1024, 2048)
ATTN_SWEEP_BK = (512, 1024, 2048)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_DB_PATH = os.path.join(_REPO, "tools", "kernel_db.json")

DB_SCHEMA = 1
ENV_FLAG = "PADDLE_TPU_KERNEL_DB"


# ---------------------------------------------------------------------------
# keys
# ---------------------------------------------------------------------------

_SHORT_DTYPE = {
    "float32": "f32", "float64": "f64", "bfloat16": "bf16",
    "float16": "f16", "int32": "i32", "int64": "i64", "int8": "i8",
    "uint8": "u8", "bool": "b1",
}


def _dt_short(dtype):
    name = np.dtype(dtype).name if str(dtype) != "bfloat16" else "bfloat16"
    return _SHORT_DTYPE.get(str(name), str(name))


def shape_signature(args, kwargs=None):
    """Stable shape/dtype signature of one example's inputs:
    ``f32[4,128],i32[40]`` — array leaves only, python scalars (block
    sizes, flags) excluded, in positional order. The DB's shape axis."""
    import jax

    parts = []
    leaves = list(args) + [v for _, v in sorted((kwargs or {}).items())]
    for a in leaves:
        if isinstance(a, (np.ndarray, jax.Array)):
            dt = _dt_short(a.dtype)
            parts.append(f"{dt}[{','.join(str(d) for d in a.shape)}]")
    return ",".join(parts)


def dominant_dtype(args, kwargs=None):
    """The record's dtype axis: the first array argument's dtype (the
    streamed operand dtype, which sets tiling and bandwidth)."""
    import jax

    leaves = list(args) + [v for _, v in sorted((kwargs or {}).items())]
    for a in leaves:
        if isinstance(a, (np.ndarray, jax.Array)):
            return _dt_short(a.dtype)
    return "?"


def db_key(kernel, sig, dtype, backend):
    """``kernel|sig|dtype|backend`` — the DB's primary key, mirroring
    how the registry keys canonical examples by kernel name."""
    return f"{kernel}|{sig}|{dtype}|{backend}"


# ---------------------------------------------------------------------------
# measurement harness
# ---------------------------------------------------------------------------

class MeasureResult:
    """One measured (kernel, inputs) point, roofline-attributed."""

    __slots__ = ("kernel", "sig", "dtype", "backend", "kernel_ms",
                 "fallback_ms", "speedup", "compile_ms", "flops",
                 "bytes_accessed", "roof", "n_samples", "warmup",
                 "config", "seed")

    def __init__(self, **kw):
        for s in self.__slots__:
            setattr(self, s, kw.get(s))

    def to_record(self, rank=0, event="measure"):
        roof = self.roof or {}
        return make_kernelbench_record(
            kernel=self.kernel, sig=self.sig, backend=self.backend,
            kernel_ms=self.kernel_ms, rank=rank, dtype=self.dtype,
            fallback_ms=self.fallback_ms, speedup=self.speedup,
            compile_ms=self.compile_ms, flops=self.flops,
            bytes_accessed=self.bytes_accessed,
            flops_frac=roof.get("flops_frac"),
            bw_frac=roof.get("bw_frac"),
            predicted_ms=roof.get("predicted_ms"),
            bound=roof.get("bound"), config=self.config,
            db_key=db_key(self.kernel, self.sig, self.dtype,
                          self.backend),
            n_samples=self.n_samples, warmup=self.warmup,
            event=event, seed=self.seed)


def _timed_call(fn, args, kwargs, warmup, k, clock):
    """AOT-compile `fn` over the ARRAY arguments (python scalars stay
    static, exactly as kernel_lint.trace_kernel_jaxprs binds them), then
    run warmup + k timed iterations and return
    (median_ms, compile_ms, samples). compile_ms is measured around
    lower().compile() — the compile-observatory discipline — so it can
    never leak into the execute median."""
    import jax

    kwargs = kwargs or {}
    arr_idx = [i for i, a in enumerate(args)
               if isinstance(a, (np.ndarray, jax.Array))]

    def wrapper(*arrs):
        full = list(args)
        for i, a in zip(arr_idx, arrs):
            full[i] = a
        return fn(*full, **kwargs)

    arrs = [args[i] for i in arr_idx]
    t0 = clock()
    compiled = jax.jit(wrapper).lower(*arrs).compile()
    compile_ms = (clock() - t0) * 1e3

    for _ in range(max(0, warmup)):
        jax.block_until_ready(compiled(*arrs))
    samples = []
    for _ in range(max(1, k)):
        t0 = clock()
        jax.block_until_ready(compiled(*arrs))
        samples.append((clock() - t0) * 1e3)
    return statistics.median(samples), compile_ms, samples


def traced_cost(reg, args, kwargs=None):
    """KN503-traced (flops, bytes_accessed) of one example run: the
    kernel-body jaxpr cost x grid steps summed over every pallas_call
    the run makes, and the revisit-aware block DMA stream. Returns
    (None, None) when capture fails (an example that cannot trace is a
    Kernel Doctor finding, not ours)."""
    from ..analysis import kernel_lint

    try:
        captures, _ = kernel_lint.capture_kernels(
            reg.fn, args, kwargs, name=reg.name)
        jaxprs = kernel_lint.trace_kernel_jaxprs(reg.fn, args, kwargs)
    except Exception:
        return None, None
    flops = 0
    bytes_accessed = 0
    for cap, jx in zip(captures, jaxprs):
        step_flops, _ = kernel_lint.count_body_cost(jx)
        flops += step_flops * cap.n_steps
        bytes_accessed += kernel_lint.counted_dma_bytes(cap)
    return int(flops), int(bytes_accessed)


def roofline(flops, bytes_accessed, time_ms, peak_flops=None,
             peak_bw=None, device_kind=None):
    """Place one measured point on the device roofline. Returns a dict:

    - achieved_flops / achieved_bw — measured rates (None without the
      corresponding count or a positive time);
    - flops_frac / bw_frac — achieved over peak, clamped to [0, 1]
      (None on CPU backends, where the peak tables answer None);
    - predicted_ms — the roofline floor max(flops/peak_flops,
      bytes/peak_bw), what `kernel_time_drift` judges measured time
      against;
    - bound — 'compute' | 'memory' by arithmetic intensity vs the
      machine balance (None when either peak is unknown).
    """
    if peak_flops is None:
        peak_flops = device_peak_flops(device_kind)
    if peak_bw is None:
        peak_bw = device_peak_hbm_bw(device_kind)
    t_s = time_ms / 1e3 if time_ms and time_ms > 0 else None
    out = {"achieved_flops": None, "achieved_bw": None,
           "flops_frac": None, "bw_frac": None,
           "predicted_ms": None, "bound": None,
           "peak_flops": peak_flops, "peak_hbm_bw": peak_bw}
    if t_s and flops:
        out["achieved_flops"] = flops / t_s
        if peak_flops:
            out["flops_frac"] = min(1.0, out["achieved_flops"]
                                    / peak_flops)
    if t_s and bytes_accessed:
        out["achieved_bw"] = bytes_accessed / t_s
        if peak_bw:
            out["bw_frac"] = min(1.0, out["achieved_bw"] / peak_bw)
    if peak_flops and peak_bw and (flops or bytes_accessed):
        t_compute = (flops or 0) / peak_flops
        t_memory = (bytes_accessed or 0) / peak_bw
        out["predicted_ms"] = max(t_compute, t_memory) * 1e3
        out["bound"] = "compute" if t_compute >= t_memory else "memory"
    return out


def measure_kernel(reg, seed=1234, warmup=2, k=5, clock=None,
                   time_fallback=True, args=None, kwargs=None,
                   config=None):
    """Measure one registration on its seeded canonical example (or on
    explicit `args`/`kwargs`): kernel median-of-k, fallback median on
    the SAME inputs, traced-cost roofline. Deterministic given `clock`
    (tests inject a fake) and `seed` (the example derives shapes AND
    values from it, the KN504 discipline)."""
    import jax

    clock = clock or time.perf_counter
    if args is None:
        rng = np.random.default_rng(seed)
        args, kwargs = reg.example(rng)
    kernel_ms, compile_ms, _ = _timed_call(
        reg.fn, args, kwargs, warmup, k, clock)
    fallback_ms = None
    speedup = None
    if time_fallback and reg.fallback is not None:
        fallback_ms, _, _ = _timed_call(
            reg.fallback, args, kwargs, warmup, k, clock)
        if kernel_ms > 0:
            speedup = fallback_ms / kernel_ms
    flops, bytes_accessed = traced_cost(reg, args, kwargs)
    backend = jax.default_backend()
    roof = roofline(flops, bytes_accessed, kernel_ms)
    res = MeasureResult(
        kernel=reg.name, sig=shape_signature(args, kwargs),
        dtype=dominant_dtype(args, kwargs), backend=backend,
        kernel_ms=kernel_ms, fallback_ms=fallback_ms, speedup=speedup,
        compile_ms=compile_ms, flops=flops,
        bytes_accessed=bytes_accessed, roof=roof, n_samples=max(1, k),
        warmup=max(0, warmup), config=config, seed=seed)
    _export_gauges(res)
    return res


def _export_gauges(res):
    """Mirror one measurement onto /metrics (telemetry.metrics_http
    scrapes monitor.snapshot_typed verbatim)."""
    name = res.kernel
    monitor.set_gauge(f"kernel.{name}.ms", float(res.kernel_ms))
    if res.speedup is not None:
        monitor.set_gauge(f"kernel.{name}.speedup", float(res.speedup))
    roof = res.roof or {}
    if roof.get("flops_frac") is not None:
        monitor.set_gauge(f"kernel.{name}.flops_frac",
                          float(roof["flops_frac"]))
    if roof.get("bw_frac") is not None:
        monitor.set_gauge(f"kernel.{name}.bw_frac",
                          float(roof["bw_frac"]))
    monitor.incr("kernel.measured")


def measure_registry(registry=None, seeds=(1234,), warmup=2, k=5,
                     clock=None):
    """Measure every registered kernel once per seed (the canonical
    example at seeds[0], the per-kernel shape/dtype sweep at the rest —
    the examples derive shapes and dtypes from the rng, so extra seeds
    ARE the sweep). Returns [MeasureResult, ...] in registry order."""
    from ..ops.kernel_registry import registered_kernels

    regs = registered_kernels() if registry is None \
        else list(registry.values())
    out = []
    for reg in regs:
        for seed in seeds:
            out.append(measure_kernel(reg, seed=seed, warmup=warmup,
                                      k=k, clock=clock))
    return out


# ---------------------------------------------------------------------------
# persistent measurement DB
# ---------------------------------------------------------------------------

def _finite(v):
    return isinstance(v, (int, float)) and math.isfinite(v)


class KernelDB:
    """tools/kernel_db.json: best-known timing + chosen config per
    (kernel, shape-signature, dtype, backend) key. `update` REFUSES
    non-finite rows (the bench_gate --update-baseline contract): a NaN
    that slips into the baseline would silently disarm every future
    comparison against it."""

    def __init__(self, path=DEFAULT_DB_PATH):
        self.path = path
        self.entries = {}
        self.comment = ""
        if path and os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
            self.entries = dict(data.get("entries", {}))
            self.comment = data.get("comment", "")

    def lookup(self, kernel, sig=None, dtype=None, backend=None):
        """Entries for one kernel, narrowed by whatever axes the caller
        knows. Returns [(key, entry), ...]."""
        out = []
        for key, e in self.entries.items():
            if e.get("kernel") != kernel:
                continue
            if sig is not None and e.get("sig") != sig:
                continue
            if dtype is not None and e.get("dtype") != dtype:
                continue
            if backend is not None and e.get("backend") != backend:
                continue
            out.append((key, e))
        return out

    def best_ms(self, kernel, sig, dtype, backend):
        e = self.entries.get(db_key(kernel, sig, dtype, backend))
        return e.get("best_ms") if e else None

    def update(self, results, keep_best=True):
        """Roll measured rows in. `results` is [MeasureResult] or
        [(key, entry_dict)]. Returns (updated_keys, refused) where
        refused is [(key, reason)] — non-finite timings never land, and
        with keep_best a slower row than the incumbent is skipped (not
        refused: losing a race is not an error)."""
        updated, refused = [], []
        for item in results:
            if isinstance(item, MeasureResult):
                key = db_key(item.kernel, item.sig, item.dtype,
                             item.backend)
                entry = {
                    "kernel": item.kernel, "sig": item.sig,
                    "dtype": item.dtype, "backend": item.backend,
                    "best_ms": item.kernel_ms,
                    "fallback_ms": item.fallback_ms,
                    "flops": item.flops,
                    "bytes_accessed": item.bytes_accessed,
                }
                if item.config:
                    entry["config"] = dict(item.config)
            else:
                key, entry = item
                entry = dict(entry)
                # the key IS the identity — backfill the lookup axes
                # from it so a hand-built (key, entry) pair can't ship
                # an entry lookup() would never find
                parts = key.split("|")
                if len(parts) == 4:
                    for axis, val in zip(
                            ("kernel", "sig", "dtype", "backend"), parts):
                        entry.setdefault(axis, val)
            ms = entry.get("best_ms")
            if not _finite(ms) or ms < 0:
                refused.append(
                    (key, f"REFUSED: non-finite best_ms {ms!r}"))
                continue
            bad = [k for k, v in entry.items()
                   if isinstance(v, float) and not math.isfinite(v)]
            if bad:
                refused.append(
                    (key, f"REFUSED: non-finite value(s) in {bad}"))
                continue
            old = self.entries.get(key)
            if keep_best and old and _finite(old.get("best_ms")) \
                    and old["best_ms"] <= ms:
                continue
            self.entries[key] = entry
            updated.append(key)
        return updated, refused

    def save(self, path=None):
        path = path or self.path
        data = {"schema": DB_SCHEMA, "comment": self.comment,
                "entries": {k: self.entries[k]
                            for k in sorted(self.entries)}}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# opt-in DB-backed config resolution (the _resolve_blocks hook)
# ---------------------------------------------------------------------------

def db_flag_path():
    """The opt-in flag: PADDLE_TPU_KERNEL_DB unset/empty/'0' -> None
    (hand-tuned defaults, no DB I/O on the hot path); '1' -> the
    checked-in tools/kernel_db.json; anything else -> that path."""
    raw = os.environ.get(ENV_FLAG, "").strip()
    if not raw or raw == "0":
        return None
    return DEFAULT_DB_PATH if raw == "1" else raw


@functools.lru_cache(maxsize=8)
def _load_db(path):
    try:
        return KernelDB(path)
    except Exception:
        return None


def clear_db_cache():
    _load_db.cache_clear()


def tuned_param(kernel, param, match=None, validate=None):
    """Resolve one tuned config value for `kernel` from the flagged DB,
    or None (caller keeps its hand-tuned default). `match` narrows on
    entry config keys (e.g. {'sq': 16384}); `validate` is a predicate
    the value must pass (feasibility re-checked at the call site — a DB
    edited by hand can never force an infeasible block). Of the
    matching entries, the fastest wins."""
    path = db_flag_path()
    if path is None:
        return None
    db = _load_db(path)
    if db is None:
        return None
    best_v, best_ms = None, None
    for _, e in db.lookup(kernel):
        cfg = e.get("config") or {}
        if param not in cfg:
            continue
        if match and any(cfg.get(k) != v for k, v in match.items()):
            continue
        v = cfg[param]
        if validate is not None and not validate(v):
            continue
        ms = e.get("best_ms")
        if not _finite(ms):
            continue
        if best_ms is None or ms < best_ms:
            best_v, best_ms = v, ms
    return best_v


def tuned_blocks(family, sq, for_bwd=False):
    """The `_resolve_blocks` consult: (block_q, block_k) for the flash
    family ('flash_fwd' / 'flash_bwd') at sequence length sq, or None.
    Entries are written by `kernellab --tune` with config
    {'sq': sq, 'block_q': bq, 'block_k': bk}."""
    kernel = "flash_bwd" if for_bwd else "flash_fwd"
    if family:
        kernel = family
    bq = tuned_param(kernel, "block_q", match={"sq": int(sq)},
                     validate=lambda v: isinstance(v, int) and v >= 128)
    bk = tuned_param(kernel, "block_k", match={"sq": int(sq)},
                     validate=lambda v: isinstance(v, int) and v >= 128)
    if bq is None or bk is None:
        return None
    return bq, bk


# ---------------------------------------------------------------------------
# config search (the autotune hook)
# ---------------------------------------------------------------------------

def _flash_fwd_vmem_feasible(bq, bk, h, budget=None):
    """KN502 feasibility for a flash-forward candidate, through the
    SAME kernel_registry.vmem_footprint model the Kernel Doctor
    projects with: q/k/v/out/lse blocks move (double-buffered), the
    acc/m/l accumulators are scratch."""
    from ..ops.kernel_registry import VMEM_BUDGET, vmem_footprint

    lanes = 128
    sub = 8
    f32 = 4
    itemsize = 4   # tune measures in f32; bf16 halves the moving set
    used = vmem_footprint(
        moving=[((1, bq, h), itemsize), ((1, bk, h), itemsize),
                ((1, bk, h), itemsize), ((1, bq, h), itemsize),
                ((1, sub, bq), f32)],
        scratch=[((bq, h), f32), ((bq, lanes), f32),
                 ((bq, lanes), f32)])
    return used <= (budget or VMEM_BUDGET)


def tune_flash_fwd(seq=1024, batch=1, heads=2, head_dim=64,
                   warmup=1, k=3, seeds=(0, 1), clock=None,
                   candidates=None):
    """Search the flash-forward (block_q, block_k) space at one shape:
    KN502 vmem_footprint as the feasibility predicate, measured
    median-of-k time as the objective, KN504 parity re-fuzz on the
    winner so tuning can never trade correctness. Returns
    (winner dict | None, [MeasureResult per feasible candidate],
    skipped list)."""
    import jax

    from ..analysis.kernel_lint import check_fallback_parity
    from ..ops import pallas_attention as pa
    from ..ops.kernel_registry import PallasKernel, get_kernel

    clock = clock or time.perf_counter
    reg = get_kernel("flash_fwd_rect")
    rng = np.random.default_rng(1234)
    q = rng.standard_normal(
        (batch, seq, heads, head_dim)).astype(np.float32)
    if candidates is None:
        candidates = [(bq, bk) for bq in ATTN_SWEEP_BQ
                      for bk in ATTN_SWEEP_BK]

    results, skipped = [], []
    for bq, bk in candidates:
        if bq > seq or bk > seq:
            skipped.append(((bq, bk), "blocks exceed seq"))
            continue
        if not _flash_fwd_vmem_feasible(bq, bk, head_dim):
            skipped.append(((bq, bk), "KN502: over the VMEM budget"))
            continue
        args = (q, q, q, True, 1.0, bq, bk)
        res = measure_kernel(
            reg, warmup=warmup, k=k, clock=clock, time_fallback=False,
            args=args, kwargs={},
            config={"sq": int(seq), "block_q": int(bq),
                    "block_k": int(bk)})
        results.append(res)
    if not results:
        return None, results, skipped

    best = min(results, key=lambda r: r.kernel_ms)
    bq, bk = best.config["block_q"], best.config["block_k"]

    # KN504 re-fuzz: the registered example with the TUNED blocks bound
    # in place of its defaults, against the registered exact fallback
    def tuned_fn(q_, k_, v_, causal, scale, block_q, block_k):
        return reg.fn(q_, k_, v_, causal, scale, bq, bk)

    def tuned_example(rng_):
        args_, kwargs_ = reg.example(rng_)
        return args_, kwargs_

    tuned_reg = PallasKernel(
        name=f"{reg.name}@bq{bq}bk{bk}", fn=tuned_fn,
        example=tuned_example, fallback=reg.fallback, tol=reg.tol,
        notes="tuned-config parity re-fuzz (kernellab --tune)")
    parity = check_fallback_parity(tuned_reg, seeds=seeds)
    winner = {
        "kernel": "flash_fwd", "sig": best.sig, "dtype": best.dtype,
        "backend": jax.default_backend(), "best_ms": best.kernel_ms,
        "config": dict(best.config),
        "parity_findings": [f.to_dict() for f in parity],
        "vmem_feasible": True,
    }
    return winner, results, skipped
