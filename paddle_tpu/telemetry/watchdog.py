"""Hang watchdog: heartbeat-armed stall detection + black-box dumps.

A pod hang has the worst symptom/cause ratio in large-scale training:
rank 3 is stuck in an all-reduce and every other rank politely waits,
so the only observable is SILENCE — no exception, no log line, no exit.
The watchdog turns silence into evidence:

- `step_opened()` / `step_closed()` (called by the health hook around
  every train step) arm and disarm a deadline; a daemon thread checks
  it a few times per deadline period.
- past the deadline, the watchdog writes a **black box**: every
  thread's Python stack (`sys._current_frames`), the open telemetry
  spans — so the stuck region is NAMED (`collective.all_reduce`,
  `pipeline.1f1b_dispatch`) not just located —, `monitor.snapshot()`
  (counters + gauges + uptime/rank), and the last-N step-record ring.
- the same `dump_black_box()` fires when an exception escapes a train
  step (HealthMonitor.on_exception), so crash and hang leave the same
  artifact.

Reference analogs: the distributed-run watchdogs in elastic training
(`distributed/elastic`) watched process liveness; here the unit is the
train step, which is what a single-controller TPU job actually stalls
on. The dump is plain JSON — `jq .threads` on a wedged pod beats
attaching a debugger to 256 hosts.
"""
import json
import os
import sys
import threading
import time
import traceback

from .. import monitor
from ..analysis import lockwatch

__all__ = ["HangWatchdog", "dump_black_box"]


def _thread_stacks():
    """Python stacks of every live thread, keyed by thread name."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, 'unknown')} (tid={tid})"
        out[label] = traceback.format_stack(frame)
    return out


def dump_black_box(reason="", dump_dir=".", ring=(), path=None, extra=None):
    """Write the black-box crash file and return its path.

    Contents: reason, pid/rank/uptime, ALL thread stacks, open
    telemetry spans (name + category + age + thread — the stuck
    collective is named here), the lockwatch lock table (holder, hold
    duration, waiters — empty unless `lockwatch.arm()` ran), the full
    monitor snapshot, and the last-N step records. Best-effort by design: a dump must never turn
    a hang into a crash, so every section degrades to an error string
    rather than raising."""
    from . import recorder as _recorder

    def _section(fn):
        try:
            return fn()
        except Exception as e:          # pragma: no cover - defensive
            return f"<unavailable: {type(e).__name__}: {e}>"

    box = {
        "kind": "health_blackbox",
        "reason": reason,
        "time_unix": time.time(),
        "pid": os.getpid(),
        "threads": _section(_thread_stacks),
        "open_spans": _section(_recorder.open_spans),
        "locks": _section(lockwatch.snapshot),
        "monitor": _section(monitor.snapshot),
        "ring": list(ring),
    }
    if extra:
        box["extra"] = extra
    if path is None:
        path = os.path.join(
            dump_dir or ".",
            f"health_blackbox_{os.getpid()}_{int(time.time() * 1000)}.json")
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(box, f, indent=2, default=repr)
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:                 # pragma: no cover - defensive
        sys.stderr.write(f"[health] black-box dump to {path} failed: {e}\n")
        return None
    sys.stderr.write(f"[health] black box dumped: {path} ({reason})\n")
    return path


class HangWatchdog:
    """Deadline heartbeat over train-step open/close events.

    wd = HangWatchdog(deadline_s=300, dump_dir="...")
    wd.start()
    wd.step_opened()   # arm      (train step begins)
    wd.step_closed()   # disarm   (train step returned)
    wd.beat()          # re-arm mid-step (a known-slow legit section)

    While armed, exceeding the deadline writes one black-box dump (per
    armed window — a 2-hour hang produces one file, not 2400) and
    advances the `health.watchdog_fires` counter. The checker thread is
    a daemon: an exiting process never blocks on it.
    """

    def __init__(self, deadline_s=300.0, dump_dir=".", ring=None,
                 poll_s=None, on_dump=None):
        self.deadline_s = float(deadline_s)
        self.dump_dir = dump_dir
        self.ring = ring if ring is not None else []
        self.on_dump = on_dump
        self._poll_s = poll_s if poll_s is not None else \
            min(max(self.deadline_s / 4.0, 0.02), 30.0)
        self._mu = lockwatch.make_lock("HangWatchdog._mu")
        self._armed_at = None             # guarded by: _mu
        self._dumped_this_window = False  # guarded by: _mu
        self._stop = threading.Event()
        self._thread = None  # guarded by: none (caller-serialized lifecycle)
        self.dumps = []      # guarded by: none (checker-thread confined)
        self.fires = 0       # guarded by: none (checker-thread confined)

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="paddle-tpu-hang-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, self._poll_s * 4))
        self._thread = None

    # -- heartbeat events ---------------------------------------------------
    def step_opened(self):
        with self._mu:
            self._armed_at = time.monotonic()
            self._dumped_this_window = False

    beat = step_opened

    def step_closed(self, record=None):
        with self._mu:
            self._armed_at = None
            self._dumped_this_window = False
        if record:
            self.ring.append(record)

    @property
    def armed(self):
        with self._mu:
            return self._armed_at is not None

    def overdue_s(self):
        """Seconds past the deadline for the current armed window
        (<= 0: not overdue / not armed). /healthz uses this."""
        with self._mu:
            if self._armed_at is None:
                return 0.0
            return (time.monotonic() - self._armed_at) - self.deadline_s

    # -- checker ------------------------------------------------------------
    def _run(self):
        while not self._stop.wait(self._poll_s):
            self._check()

    def _check(self):
        with self._mu:
            armed_at = self._armed_at
            already = self._dumped_this_window
            if armed_at is None or already:
                return
            stalled_s = time.monotonic() - armed_at
            if stalled_s <= self.deadline_s:
                return
            self._dumped_this_window = True
        self.fires += 1
        monitor.incr("health.watchdog_fires")
        path = self.dump(
            reason=f"train step stalled for {stalled_s:.1f}s "
                   f"(deadline {self.deadline_s:.1f}s)")
        if self.on_dump is not None:
            try:
                self.on_dump(path)
            except Exception:            # pragma: no cover - defensive
                pass

    def dump(self, reason=""):
        path = dump_black_box(reason=reason, dump_dir=self.dump_dir,
                              ring=list(self.ring))
        if path:
            self.dumps.append(path)
        return path
