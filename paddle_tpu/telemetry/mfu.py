"""MFU / throughput accounting: model FLOPs x measured time / device peak.

One home for the three inputs every MFU number needs:

- model FLOPs per step — either analytic (PaLM-style 6N + attention term,
  `model_flops_per_token`), hook-counted (`hapi.flops.flops`), or exact
  from the compiled program (`hapi.flops.flops_compiled` /
  `cost_model.CostModel` — XLA's own cost analysis);
- measured step time — from the TelemetryRecorder;
- device peak FLOP/s — `device_peak_flops` below, keyed on the JAX
  device_kind string (bf16 peaks; the table bench.py's MFU numbers have
  always used, now shared).
"""
import jax


# bf16 peak FLOP/s per chip by device kind substring
PEAK_FLOPS_BY_KIND = {
    "v2": 45e12, "v3": 123e12, "v4": 275e12,
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v6 lite": 918e12, "v6e": 918e12,
}

# peak HBM bandwidth (bytes/s) per chip by device kind substring — the
# second axis of the roofline the kernel observatory
# (telemetry/kernel_obs.py) places measured kernels on; same
# longest-substring keying as the FLOPs table so the two can never
# disagree about which chip they describe
PEAK_HBM_BW_BY_KIND = {
    "v2": 700e9, "v3": 900e9, "v4": 1228e9,
    "v5 lite": 819e9, "v5e": 819e9, "v5p": 2765e9,
    "v6 lite": 1638e9, "v6e": 1638e9,
}


def _match_kind(table, kind):
    if kind is None:
        try:
            kind = jax.devices()[0].device_kind
        except Exception:
            return None
    kind = str(kind).lower()
    for key, val in sorted(table.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return None


def device_peak_flops(kind=None):
    """Peak bf16 FLOP/s for a device-kind string (longest-substring match,
    e.g. 'TPU v5 lite' -> 197e12). kind=None reads the default jax device.
    Returns None when unknown (CPU backends) — callers treat that as
    'MFU not computable' and report 0.0."""
    return _match_kind(PEAK_FLOPS_BY_KIND, kind)


def device_peak_hbm_bw(kind=None):
    """Peak HBM bandwidth (bytes/s) for a device-kind string, same
    matching rules as device_peak_flops. None when unknown (CPU) —
    the roofline's bandwidth fraction is then not computable."""
    return _match_kind(PEAK_HBM_BW_BY_KIND, kind)


def model_flops_per_token(n_params, num_layers=0, hidden_size=0, seq_len=0):
    """PaLM-style train FLOPs per token: 6N for the parameter matmuls
    (fwd 2N + bwd 4N) plus 12*L*H*S for self-attention score/value work."""
    return 6 * int(n_params) + 12 * int(num_layers) * int(hidden_size) \
        * int(seq_len)


def mfu(flops_per_step, step_time_s, peak_flops=None, n_devices=1):
    """Model FLOPs utilization in [0, ~1]: achieved model FLOP/s over the
    aggregate peak. Returns 0.0 (finite) when the peak is unknown or the
    window is degenerate, never NaN/inf."""
    if peak_flops is None:
        peak_flops = device_peak_flops()
    if not peak_flops or not step_time_s or step_time_s <= 0:
        return 0.0
    return float(flops_per_step) / float(step_time_s) \
        / (float(peak_flops) * max(1, int(n_devices)))


def flops_drift(compiled_flops, analytic_flops):
    """Relative drift of the compiled program's cost-analysis FLOPs from
    the analytic number the MFU accounting multiplies by: (compiled -
    analytic) / analytic. MFU reports analytic_flops / (time * peak), so
    positive drift = the analytic table UNDERCOUNTS and the reported MFU
    UNDERSTATES real utilization; negative drift = the table overcounts
    and the reported MFU is inflated. None when either side is
    missing/zero (no cross-check possible)."""
    try:
        c, a = float(compiled_flops), float(analytic_flops)
    except (TypeError, ValueError):
        return None
    if c <= 0 or a <= 0:
        return None
    return (c - a) / a


def train_step_flops(loss_fn, example_batch, model=None):
    """EXACT per-step FLOPs: lower loss_fn through XLA with backprop (the
    `hapi.flops.flops_compiled` feedback loop — fusion and the dL/dW
    contractions included) and read the compiler's own cost analysis.
    Returns None when the backend refuses cost analysis; callers fall back
    to the analytic `model_flops_per_token` formula."""
    try:
        from ..hapi.flops import flops_compiled
        got = flops_compiled(loss_fn, list(example_batch),
                             backprop=True, net=model)
        flops = float(got.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None
