"""paddle_tpu.telemetry — the training flight recorder.

Unifies the three older observability stubs into one step-level layer:

- `profiler.py` host spans (RecordEvent)  -> `telemetry.span` /
  recorder span buffer + multi-rank Chrome-trace export;
- `monitor.py` counters                   -> advanced automatically per
  recorded step (`telemetry.steps`, `telemetry.compile_cache_*`);
- `distributed/metrics.py` eval stats     -> unchanged (eval-metric math),
  but per-step comm/step telemetry now lives here.

Reference analogs: `platform/profiler.h` RecordEvent + DeviceTracer and
`tools/CrossStackProfiler`'s per-rank merge; JAX-era device detail stays
on `jax.profiler` (XPlane/TensorBoard) — this layer owns the host-side
step ledger: wall time, compile vs. execute split, tokens/sec, MFU,
memory, per-collective time.

Entry points:
- TelemetryRecorder — per-step JSONL records; context-activate it and
  `jit.TrainStep` / `distributed.ShardedTrainStep` record themselves.
- StepTimer — explicit jax.stages AOT compile-cache wrapper.
- hapi.callbacks.TelemetryCallback — Model.fit integration.
- sink.export_chrome_tracing / tools/trace_check.py — trace tooling.
"""
from . import mfu  # noqa: F401
from . import sink  # noqa: F401
from .mfu import (  # noqa: F401
    device_peak_flops, model_flops_per_token, train_step_flops)
from .recorder import (  # noqa: F401
    StepTimer, TelemetryRecorder, auto_step, current_recorder, span)
from .sink import (  # noqa: F401
    JsonlSink, export_chrome_tracing, make_phase_record, make_step_record,
    read_jsonl, validate_step_record)

__all__ = [
    "TelemetryRecorder", "StepTimer", "span", "auto_step",
    "current_recorder", "JsonlSink", "read_jsonl", "make_step_record",
    "make_phase_record", "validate_step_record", "export_chrome_tracing",
    "device_peak_flops", "model_flops_per_token", "train_step_flops",
    "mfu", "sink",
]
