"""paddle_tpu.telemetry — the training flight recorder.

Unifies the three older observability stubs into one step-level layer:

- `profiler.py` host spans (RecordEvent)  -> `telemetry.span` /
  recorder span buffer + multi-rank Chrome-trace export;
- `monitor.py` counters                   -> advanced automatically per
  recorded step (`telemetry.steps`, `telemetry.compile_cache_*`);
- `distributed/metrics.py` eval stats     -> unchanged (eval-metric math),
  but per-step comm/step telemetry now lives here.

Reference analogs: `platform/profiler.h` RecordEvent + DeviceTracer and
`tools/CrossStackProfiler`'s per-rank merge; JAX-era device detail stays
on `jax.profiler` (XPlane/TensorBoard) — this layer owns the host-side
step ledger: wall time, compile vs. execute split, tokens/sec, MFU,
memory, per-collective time.

Entry points:
- TelemetryRecorder — per-step JSONL records; context-activate it and
  `jit.TrainStep` / `distributed.ShardedTrainStep` record themselves.
- StepTimer — explicit jax.stages AOT compile-cache wrapper.
- hapi.callbacks.TelemetryCallback — Model.fit integration.
- sink.export_chrome_tracing / tools/trace_check.py — trace tooling.
- health.HealthConfig / HealthMonitor — jit-safe numerics taps +
  anomaly detection (`health=` on the train steps); watchdog.HangWatchdog
  — stall detection with black-box dumps; metrics_http.MetricsServer —
  live /healthz, /metrics (Prometheus), /steps scrape endpoint;
  tools/healthwatch.py replays the same anomaly rules offline.
- compile_obs.CompileObservatory — the compile observatory: context-
  activate it and every train-step (re)compile is recorded with a
  cause diff, compiled-HBM breakdown (`memory_analysis()`), cost-model
  cross-checks and a recompile-storm rule; tools/compile_report.py
  renders/replays the JSONL offline.
- mem_obs.MemoryObservatory — the memory observatory: a live HBM
  ledger over `jax.live_arrays()` with byte attribution into
  params/opt_state/kv/workspace/other buckets, KV-pool occupancy
  telemetry, reconciliation against the compile observatory's static
  projection, and capture-on-failure OOM postmortems;
  tools/memwatch.py renders/replays the JSONL offline.
"""
from . import compile_obs  # noqa: F401
from . import health  # noqa: F401
from . import mem_obs  # noqa: F401
from . import metrics_http  # noqa: F401
from . import mfu  # noqa: F401
from . import reqtrace  # noqa: F401
from . import sink  # noqa: F401
from . import watchdog  # noqa: F401
from .health import (  # noqa: F401
    Anomaly, AnomalyDetector, HealthConfig, HealthError, HealthMonitor)
from .compile_obs import (  # noqa: F401
    CompileObservatory, CompileSignature, RecompileTracker,
    current_observatory, diff_signatures, signature_of)
from .compile_obs import dispatch as observed_dispatch  # noqa: F401
from .mem_obs import (  # noqa: F401
    MemoryObservatory, is_oom, register_provider, snapshot_ledger)
from .metrics_http import MetricsServer  # noqa: F401
from .mfu import (  # noqa: F401
    device_peak_flops, model_flops_per_token, train_step_flops)
from .recorder import (  # noqa: F401
    StepTimer, TelemetryRecorder, auto_step, current_recorder, open_spans,
    span)
from .reqtrace import (  # noqa: F401
    RequestTrace, RequestTracer, decompose, dominant_cause,
    trace_chrome_spans)
from .sink import (  # noqa: F401
    JsonlSink, export_chrome_tracing, make_bench_record, make_ckpt_record,
    make_memsnap_record, make_phase_record, make_reqtrace_record,
    make_serving_record, make_step_record, read_jsonl,
    validate_step_record)
from .watchdog import HangWatchdog, dump_black_box  # noqa: F401

__all__ = [
    "TelemetryRecorder", "StepTimer", "span", "auto_step",
    "current_recorder", "open_spans", "JsonlSink", "read_jsonl",
    "make_step_record", "make_phase_record", "make_ckpt_record",
    "make_bench_record", "make_serving_record", "make_reqtrace_record",
    "make_memsnap_record",
    "MemoryObservatory", "is_oom", "register_provider", "snapshot_ledger",
    "RequestTrace", "RequestTracer", "decompose", "dominant_cause",
    "trace_chrome_spans",
    "validate_step_record", "export_chrome_tracing",
    "device_peak_flops", "model_flops_per_token", "train_step_flops",
    "HealthConfig", "HealthMonitor", "HealthError", "Anomaly",
    "AnomalyDetector", "HangWatchdog", "dump_black_box", "MetricsServer",
    "CompileObservatory", "CompileSignature", "RecompileTracker",
    "current_observatory", "diff_signatures", "signature_of",
    "observed_dispatch",
    "mfu", "sink", "health", "watchdog", "metrics_http", "compile_obs",
    "reqtrace", "mem_obs",
]
