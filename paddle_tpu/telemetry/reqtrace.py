"""Per-request tracer for the serving engine: span timelines + tail
attribution (the Dapper move applied to continuous batching).

The serving stack has five mechanisms that can each make one request
slow — queueing behind admission control, evict-by-recompute
preemption, warm restarts after transient step faults, chunked prefill
of long prompts, and copy-on-write forks of shared prefix blocks — and
until now its only latency observability was aggregate TTFT/TPOT
percentile gauges. A p99 outlier was a number; this module makes it a
TIMELINE that names its cause:

- **RequestTrace** — one request's ordered span list. Spans TILE the
  request's [submit, finish] wall-clock interval: every span begins
  where the previous one ended, so the durations sum to the end-to-end
  latency BY CONSTRUCTION, and `tools/trace_check.py`'s decomposition
  cross-rule turns any producer bug (a dropped event, an out-of-order
  append, a clock mix-up) into a validation failure. Decode steps
  COALESCE into one span per consecutive stretch at engine-step
  boundaries — O(1) bookkeeping per request per step, never per-token
  span appends — and the tracer adds no traced values to any compiled
  step, so no compile-signature family widens (the serving smoke
  asserts zero recompiles under tracing).
- **RequestTracer** — the engine-side collector: every completed trace
  lands as a schema-validated `kind=reqtrace` record through the
  engine's sink, a bounded slowest-K exemplar heap keeps full timelines
  for the tail requests (`/traces` on the serving HTTP front serves
  them), and `spans`/`rank` duck-type the recorder protocol so
  `sink.export_chrome_tracing` renders per-request lanes next to
  engine-step spans.
- **decompose / dominant_cause** — the attribution vocabulary shared by
  `tools/tail_report.py`, the `tail_latency` anomaly rule
  (telemetry.health.AnomalyDetector — same rule in flight and in
  offline replays, per the PR-3 pattern), and tests: every span maps to
  one of CAUSES (queue_wait, preemption, restart, prefill, cow_fork,
  decode, collective, transfer, other), with replayed prefill chunks
  charged to the preemption/restart that forced the recompute rather
  than to prefill. Collective waits and host<->device transfers get
  their own columns — charging comm time to `other` hid exactly the
  costs a multi-chip serving mesh needs attributed.
"""
import heapq
import itertools

from .. import monitor
from ..analysis import lockwatch
from .sink import REQTRACE_SPAN_KINDS, make_reqtrace_record

__all__ = ["RequestTrace", "RequestTracer", "CAUSES",
           "PATHOLOGICAL_CAUSES", "decompose", "dominant_cause",
           "trace_chrome_spans"]

# the attribution vocabulary: every span kind maps onto exactly one of
# these buckets (decompose below); "other" absorbs the zero-duration
# markers (admit/finalize) and anything a newer producer adds.
# collective (cross-chip sync waits) and transfer (host<->device
# staging) carry their own buckets: they are real work like decode,
# but work the MESH does — a tail report that lumped them into
# "other" could not say whether a slow request waited on compute or
# on the interconnect
CAUSES = ("queue_wait", "preemption", "restart", "prefill", "cow_fork",
          "decode", "collective", "transfer", "other")
# causes that are a PROBLEM when they dominate a request's latency —
# decode and prefill are the work the user asked for; these are the
# serving stack's own mechanisms getting in the way
PATHOLOGICAL_CAUSES = ("queue_wait", "preemption", "restart", "cow_fork")


class RequestTrace:
    """One request's span timeline. The engine (and scheduler) call the
    note_* hooks at event boundaries; `_cursor` tracks the end of the
    last span so every append tiles the wall clock. All times are
    process-monotonic seconds (the clock `Request.submit_time` uses)."""

    __slots__ = ("rid", "t0", "spans", "outcome", "e2e_ms", "_cursor",
                 "_dec_end", "_dec_tokens", "_in_queue",
                 "_requeue_reason", "_replay_cause", "_max_prefilled")

    def __init__(self, rid, t0):
        self.rid = rid
        self.t0 = float(t0)
        self.spans = []
        self.outcome = None
        self.e2e_ms = None
        self._cursor = self.t0
        self._dec_end = None         # open decode segment end, or None
        self._dec_tokens = 0
        self._in_queue = True        # waiting (initially, and on requeue)
        self._requeue_reason = None  # why the NEXT queued span exists
        self._replay_cause = None    # attribution for replayed chunks
        self._max_prefilled = 0      # high-water mark of written positions

    # -- span plumbing ------------------------------------------------------
    def _push(self, kind, t1, **attrs):
        t0 = self._cursor
        if t1 < t0:                  # defensive: clocks are monotonic,
            t1 = t0                  # but never emit a negative span
        span = {"kind": kind,
                "t0_ms": round((t0 - self.t0) * 1000.0, 4),
                "dur_ms": round((t1 - t0) * 1000.0, 4)}
        for k, v in attrs.items():
            if v is not None:
                span[k] = v
        self.spans.append(span)
        self._cursor = t1

    def _flush_decode(self):
        """Close the open coalesced-decode segment, if any."""
        if self._dec_end is None:
            return
        end, n = self._dec_end, self._dec_tokens
        self._dec_end = None
        self._dec_tokens = 0
        self._push("decode", end, n_tokens=n)

    # -- engine hooks -------------------------------------------------------
    def note_admit(self, t, queue_depth=None, prefix_cached_tokens=None,
                   predicted_wait_ms=None):
        """Admission out of the waiting queue: closes the queued span
        (reason = submit, or why the request was requeued) and stamps
        the decision — including the prefix-cache hit — as a
        zero-duration `admit` span."""
        reason = self._requeue_reason or "submit"
        self._requeue_reason = None
        self._in_queue = False
        self._push("queued", t, reason=reason)
        self._push("admit", t, queue_depth=queue_depth,
                   prefix_cached_tokens=prefix_cached_tokens or None,
                   predicted_wait_ms=predicted_wait_ms)

    def note_requeue(self, t, reason, n_prefilled=None):
        """Preemption or warm-restart requeue: the marker span, then
        back to the queue. `reason` in ('preempt', 'restart')."""
        self._flush_decode()
        kind = "preempt" if reason == "preempt" else "restart_replay"
        self._push(kind, t, lost_positions=n_prefilled)
        self._requeue_reason = reason
        self._replay_cause = "preemption" if reason == "preempt" \
            else "restart"
        self._in_queue = True

    def note_prefill_chunk(self, t, p0, n_tokens):
        """One chunked-prefill dispatch covering positions
        [p0, p0 + n_tokens). Chunks re-covering positions the request
        had already written before a requeue are REPLAY — their cost is
        the preemption's/restart's, not the prompt's."""
        self._flush_decode()
        attrs = {"p0": int(p0), "n_tokens": int(n_tokens)}
        if p0 < self._max_prefilled and self._replay_cause is not None:
            attrs["replay"] = True
            attrs["replay_cause"] = self._replay_cause
        self._max_prefilled = max(self._max_prefilled, int(p0) + int(n_tokens))
        self._push("prefill_chunk", t, **attrs)

    def note_cow_fork(self, t):
        """Copy-on-write fork of a shared block before a write."""
        self._flush_decode()
        self._push("cow_fork", t)

    def note_decode(self, t):
        """One decode-step token for this request: O(1) — extends the
        open coalesced segment instead of appending a span per token."""
        self._dec_end = t
        self._dec_tokens += 1

    def note_shed(self, t, queue_depth=None, reason=None):
        """Admission rejected the request up front: the whole life was
        queue time, stamped with the shed verdict."""
        self._push("queued", t, reason="submit")
        self._push("shed", t, queue_depth=queue_depth, reason=reason)
        self.outcome = "shed"
        self.e2e_ms = round((t - self.t0) * 1000.0, 4)

    def finish(self, t, outcome):
        """Terminal transition: close any open decode segment, account
        time still spent waiting (a request cancelled/expired in the
        queue never saw an admit), and stamp the finalize span."""
        self._flush_decode()
        if self._in_queue and t > self._cursor:
            self._push("queued", t,
                       reason=self._requeue_reason or "submit")
        self._push("finalize", t, outcome=outcome)
        self.outcome = outcome
        self.e2e_ms = round((t - self.t0) * 1000.0, 4)


class RequestTracer:
    """The engine-side trace collector: hands out RequestTrace objects,
    emits completed traces as `kind=reqtrace` records through the sink,
    and keeps the slowest-K full timelines in a bounded exemplar heap
    for `/traces` and the Chrome export. Thread-safe (the engine lock
    serializes the note_* hooks; finish/timelines may race a scrape)."""

    def __init__(self, engine_id=0, rank=0, sink=None, exemplar_k=32):
        self.engine_id = int(engine_id)
        self.rank = int(rank)
        self.exemplar_k = int(exemplar_k)
        self._sink = sink   # threadlint: type=JsonlSink
        self._mu = lockwatch.make_lock("RequestTracer._mu")
        self._heap = []              # guarded by: _mu — (e2e_ms, seq, record) min-heap
        self._seq = itertools.count()   # guarded by: _mu
        self.n_traces = 0            # guarded by: _mu

    def start(self, rid, t0):
        return RequestTrace(rid, t0)

    def _note(self, rec):
        with self._mu:
            self.n_traces += 1
            item = (rec.get("e2e_ms", 0.0), next(self._seq), rec)
            if len(self._heap) < self.exemplar_k:
                heapq.heappush(self._heap, item)
            elif item[0] > self._heap[0][0]:
                heapq.heapreplace(self._heap, item)
        monitor.incr("serving.traces")
        if self._sink is not None:
            self._sink.write(rec)
        return rec

    def finish(self, req, t):
        """Finalize `req`'s trace at time `t` (its terminal state is
        already set) and emit the record. Idempotent — a second
        finalize attempt on the same trace is a no-op."""
        tr = getattr(req, "trace", None)
        if tr is None or tr.outcome is not None:
            return None
        tr.finish(t, req.state)
        return self._note(make_reqtrace_record(
            rid=req.rid, outcome=tr.outcome, spans=tr.spans,
            e2e_ms=tr.e2e_ms, rank=self.rank, engine=self.engine_id,
            t0_s=tr.t0, ttft_ms=req.ttft_ms(), tpot_ms=req.tpot_ms(),
            queue_wait_ms=req.queue_wait_ms(),
            n_tokens=len(req.out_tokens), prompt_len=len(req.prompt),
            preemptions=req.preemptions,
            request_id=getattr(req, "request_id", None)))

    def record_shed(self, req, t, queue_depth=None, reason=None):
        """A request admission rejected up front: its trace is the
        verdict (queued + shed spans), outcome 'shed'."""
        tr = getattr(req, "trace", None) or RequestTrace(
            req.rid, req.submit_time)
        tr.note_shed(t, queue_depth=queue_depth, reason=reason)
        return self._note(make_reqtrace_record(
            rid=req.rid, outcome="shed", spans=tr.spans,
            e2e_ms=tr.e2e_ms, rank=self.rank, engine=self.engine_id,
            t0_s=tr.t0, prompt_len=len(req.prompt),
            request_id=getattr(req, "request_id", None)))

    # -- consumers ----------------------------------------------------------
    def timelines(self, n=None):
        """The exemplar ring's records, slowest first (what `/traces`
        serves)."""
        with self._mu:
            items = sorted(self._heap, key=lambda it: it[0], reverse=True)
        recs = [rec for _, _, rec in items]
        return recs if n is None else recs[:max(0, int(n))]

    @property
    def spans(self):
        """Chrome-trace span dicts for the exemplar timelines — the
        recorder duck-type `sink.export_chrome_tracing` consumes, so
        per-request lanes merge into the same multi-rank trace as
        engine-step / collective spans."""
        return trace_chrome_spans(self.timelines(), rank=self.rank)


def trace_chrome_spans(records, rank=0):
    """Render reqtrace records (each carrying its absolute `t0_s`) as
    chrome-export span dicts: one lane (tid) per request, span names
    `kind`, cat 'reqtrace', request identity in args. Times stay on the
    process monotonic clock the recorder's perf_counter spans share on
    this platform."""
    out = []
    for rec in records:
        base = rec.get("t0_s")
        if base is None:
            continue
        rid = rec.get("rid", 0)
        for sp in rec.get("spans", ()):
            args = {k: v for k, v in sp.items()
                    if k not in ("kind", "t0_ms", "dur_ms")}
            args["rid"] = rid
            out.append({
                "name": f"req{rid}/{sp['kind']}",
                "t0": base + sp["t0_ms"] / 1000.0,
                "dur": sp["dur_ms"] / 1000.0,
                "tid": 10000 + int(rid),
                "cat": "reqtrace",
                "rank": rank,
                "args": args,
            })
    return out


def decompose(rec):
    """Latency decomposition of one reqtrace record: {cause: ms} over
    the CAUSES vocabulary. Replayed prefill chunks are charged to the
    preemption/restart that forced them."""
    causes = dict.fromkeys(CAUSES, 0.0)
    for sp in rec.get("spans", ()):
        kind = sp.get("kind")
        if kind not in REQTRACE_SPAN_KINDS:
            continue
        dur = sp.get("dur_ms")
        if not isinstance(dur, (int, float)) or dur != dur or dur < 0:
            continue
        if kind == "queued":
            reason = sp.get("reason", "submit")
            key = {"submit": "queue_wait", "preempt": "preemption",
                   "restart": "restart"}.get(reason, "queue_wait")
        elif kind == "prefill_chunk":
            key = sp["replay_cause"] \
                if sp.get("replay") and sp.get("replay_cause") in CAUSES \
                else "prefill"
        elif kind == "decode":
            key = "decode"
        elif kind == "cow_fork":
            key = "cow_fork"
        elif kind == "preempt":
            key = "preemption"
        elif kind == "restart_replay":
            key = "restart"
        elif kind == "shed":
            key = "queue_wait"
        elif kind == "collective":
            key = "collective"
        elif kind == "transfer":
            key = "transfer"
        else:                        # admit / finalize markers
            key = "other"
        causes[key] += float(dur)
    return causes


def dominant_cause(rec):
    """(cause, ms, fraction-of-e2e) for the largest contributor. The
    fraction denominator is the recorded e2e_ms when present (so a
    doctored non-summing trace cannot inflate its own fractions), else
    the span total."""
    causes = decompose(rec)
    total = rec.get("e2e_ms")
    if not isinstance(total, (int, float)) or total <= 0:
        total = sum(causes.values())
    cause = max(causes, key=lambda k: causes[k])
    ms = causes[cause]
    return cause, ms, (ms / total if total else 0.0)
