"""Memory observatory: live HBM ledger, KV occupancy telemetry, OOM
forensics, and the admission-headroom gauge.

The memory sibling of the compile (compile_obs), kernel (kernel_obs)
and mesh (comm_obs) observatories. Those three close the loop on what
the chip COMPILES, COMPUTES and MOVES; until now nothing closed it on
what the chip HOLDS: `compile_obs` captures only the static
``memory_analysis()`` projection, the serving BlockPool's occupancy
never reaches /metrics, and an allocation failure kills the process
with no forensic record. This module is the live side:

- **ledger** — `snapshot_ledger` walks ``jax.live_arrays()`` and
  attributes every live byte into exactly one bucket (params /
  opt_state / kv / workspace / other) via the provider registry below;
  `other` absorbs allocator bytes the live-array walk cannot see
  (``device.memory_stats()['bytes_in_use']`` minus the live sum, when
  the backend reports stats at all — CPU does not, so there `other`
  is 0 and total IS the live sum). The buckets PARTITION the total by
  construction, which is what lets tools/trace_check.py recompute the
  sum from each record's own fields.
- **provider registry** — `register_provider(name, bucket, owner,
  fn)`: the optimizer tags its per-param state (and masters), the
  paged KV cache tags its block arenas. Providers are queried FRESH at
  snapshot time (arrays are replaced every step, so tagging
  identities once would rot) and hold their owner only by weakref — a
  dead owner silently drops out of the ledger instead of pinning its
  arrays live.
- **MemoryObservatory** — samples the ledger on a step cadence into
  typed ``kind=memsnap`` records (telemetry/sink.make_memsnap_record)
  through the existing sink/validator, mirrors ``mem.*`` gauges on
  /metrics, reconciles each snapshot against the compile observatory's
  static projection (the `mem_projection_drift` rule, latched per
  family), and feeds the `hbm_pressure` / `kv_thrash` rules
  (telemetry/health.py). Every reference a rule judges against —
  budget, projection, eviction/admission rates — rides ON the record,
  so healthwatch replay and the in-flight detector see identical
  numbers (the commbench db_ms stance).
- **OOM forensics** — `is_oom` recognizes an allocation failure
  (RESOURCE_EXHAUSTED / XlaRuntimeError OOM / MemoryError);
  `capture_postmortem` writes an ``event=postmortem`` record carrying
  the last ledger, the top-K live arrays by bytes, the KV pool state
  and the active compile-signature families — so a dead run is
  diagnosable offline via ``memwatch --postmortem``.

The serving engine attaches an observatory when `EngineConfig` declares
an HBM budget, samples it in `step()`, exposes the
``serving.mem_headroom_bytes`` gauge its admission path consults, and
captures a postmortem before its restart protocol tears the arenas
down. CLI: tools/memwatch.py (--smoke / --selfcheck / --postmortem).
"""
import threading
import weakref

from .. import monitor
from .sink import make_memsnap_record

__all__ = [
    "BUCKETS", "MemoryObservatory", "capture_postmortem",
    "device_bytes_in_use", "is_oom", "register_provider",
    "registered_providers", "snapshot_ledger", "unregister_provider",
]

# the attribution buckets, in ledger order (sink.MEMSNAP_BUCKETS minus
# the _bytes suffix); every live array lands in exactly one — untagged
# arrays are workspace (activations, donated temps, host staging)
BUCKETS = ("params", "opt_state", "kv", "workspace", "other")

# ---------------------------------------------------------------------------
# provider registry (the tagging hooks)
# ---------------------------------------------------------------------------

_PROVIDERS = {}          # name -> (bucket, weakref-to-owner, fn)
_PROVIDER_LOCK = threading.Lock()
_PROVIDER_SEQ = [0]


def register_provider(name, bucket, owner, fn):
    """Register a byte-bucket provider: `fn(owner)` returns the
    CURRENT arrays belonging to `bucket` (params / opt_state / kv).
    The owner is held by weakref only — when it dies the provider
    drops out of the next snapshot and is garbage-collected from the
    registry, so tagging can never extend an arena's lifetime (the
    engine rebuilds its KV cache on restart; the old one must stay
    collectible). Returns the unique registry name (`name#<n>`)."""
    if bucket not in BUCKETS:
        raise ValueError(f"unknown bucket {bucket!r} "
                         f"(expected one of {BUCKETS})")
    with _PROVIDER_LOCK:
        _PROVIDER_SEQ[0] += 1
        key = f"{name}#{_PROVIDER_SEQ[0]}"
        _PROVIDERS[key] = (bucket, weakref.ref(owner), fn)
    return key


def unregister_provider(key):
    with _PROVIDER_LOCK:
        _PROVIDERS.pop(key, None)


def registered_providers():
    """[(name, bucket), ...] of providers whose owner is still alive."""
    with _PROVIDER_LOCK:
        items = list(_PROVIDERS.items())
    return [(k, bucket) for k, (bucket, ref, _fn) in items
            if ref() is not None]


def _query_providers():
    """Yield (bucket, arrays) per live provider; reap dead owners."""
    with _PROVIDER_LOCK:
        items = list(_PROVIDERS.items())
    dead = []
    out = []
    for key, (bucket, ref, fn) in items:
        owner = ref()
        if owner is None:
            dead.append(key)
            continue
        try:
            arrs = fn(owner)
        except Exception:
            continue          # a broken provider must not kill sampling
        if arrs:
            out.append((bucket, arrs))
    if dead:
        with _PROVIDER_LOCK:
            for key in dead:
                _PROVIDERS.pop(key, None)
    return out


# ---------------------------------------------------------------------------
# the ledger walk
# ---------------------------------------------------------------------------

def device_bytes_in_use(device=None):
    """Allocator bytes_in_use from ``device.memory_stats()``, or None
    when the backend keeps no stats (CPU) — the ledger then has no
    'other' slack and the live-array sum IS the total."""
    import jax
    try:
        dev = device if device is not None else jax.devices()[0]
        stats = dev.memory_stats()
        if isinstance(stats, dict):
            v = stats.get("bytes_in_use")
            if isinstance(v, (int, float)) and v >= 0:
                return int(v)
    except Exception:
        pass
    return None


def snapshot_ledger(top_k=8, device=None):
    """Walk the live arrays once and attribute every byte.

    Returns a plain dict: per-bucket byte sums (`<bucket>_bytes`),
    `total_bytes`, `n_arrays`, and the `top_arrays` listing
    ([{bytes, bucket, shape, dtype}, ...] descending by bytes, length
    <= top_k) the postmortem record ships. Tag membership is queried
    FRESH from the provider registry — a step's functional updates
    replace the underlying arrays, so identity tags would be stale by
    the next sample."""
    import jax
    try:
        live = [a for a in jax.live_arrays()
                if getattr(a, "nbytes", None) is not None]
    except Exception:
        live = []
    tagged = {}
    for bucket, arrs in _query_providers():
        for a in arrs:
            tagged[id(a)] = bucket
    sums = {b: 0 for b in BUCKETS}
    rows = []
    for a in live:
        nb = int(a.nbytes)
        bucket = tagged.get(id(a), "workspace")
        sums[bucket] += nb
        rows.append((nb, bucket, a))
    live_sum = sum(sums.values())
    in_use = device_bytes_in_use(device)
    if in_use is not None and in_use > live_sum:
        # allocator bytes the live-array walk cannot see: fragmentation,
        # donated-but-unreclaimed buffers, runtime scratch
        sums["other"] = in_use - live_sum
    rows.sort(key=lambda r: r[0], reverse=True)
    top = [{"bytes": nb, "bucket": bucket,
            "shape": list(getattr(a, "shape", ()) or ()),
            "dtype": str(getattr(a, "dtype", "?"))}
           for nb, bucket, a in rows[:max(0, int(top_k))]]
    led = {f"{b}_bytes": sums[b] for b in BUCKETS}
    led["total_bytes"] = sum(sums.values())
    led["n_arrays"] = len(live)
    led["top_arrays"] = top
    return led


# ---------------------------------------------------------------------------
# OOM recognition
# ---------------------------------------------------------------------------

def is_oom(exc):
    """True when `exc` is an allocation failure: XLA surfaces HBM
    exhaustion as RESOURCE_EXHAUSTED (XlaRuntimeError), host allocators
    as MemoryError. String-matched, not type-matched — the concrete
    exception class moved across jaxlib versions and forensics must
    not depend on which one this build ships."""
    if isinstance(exc, MemoryError):
        return True
    text = f"{type(exc).__name__}: {exc}"
    return "RESOURCE_EXHAUSTED" in text or "Out of memory" in text \
        or "out of memory" in text


# ---------------------------------------------------------------------------
# the observatory
# ---------------------------------------------------------------------------

class MemoryObservatory:
    """Step-cadence HBM sampler -> typed memsnap records.

    `sink` takes the records (None -> in-memory only; `.records` keeps
    the tail either way); `health` is an AnomalyDetector fed each
    record in flight (the same rules healthwatch replays offline);
    `hbm_budget_bytes` anchors the `hbm_pressure` rule and the
    headroom gauge — None means no budget was declared, so the rule
    has no jurisdiction and headroom is None (the comm_obs no-DB
    stance); `kv_source` is a zero-arg callable returning the serving
    engine's pool/scheduler accounting dict (blocks_total/held/free/
    cached, cumulative evictions/admissions + per-class dicts);
    `projection_bytes` is the compile observatory's static HBM
    projection (resolved from `compile_obs.current_observatory()` when
    not given), latched per `projection_family`."""

    def __init__(self, sink=None, rank=0, health=None,
                 hbm_budget_bytes=None, kv_source=None,
                 projection_bytes=None, projection_family="default",
                 engine=None, top_k=8, keep=64):
        self.sink = sink
        self.rank = int(rank)
        self.health = health
        self.hbm_budget_bytes = None if hbm_budget_bytes is None \
            else int(hbm_budget_bytes)
        self.kv_source = kv_source
        self.projection_bytes = None if projection_bytes is None \
            else int(projection_bytes)
        self.projection_family = str(projection_family)
        self.engine = engine
        self.top_k = int(top_k)
        self.keep = int(keep)
        self.records = []
        self.last = None
        self._prev_kv = None      # (step, evictions, admissions)

    # -- projection -------------------------------------------------------

    def _projection(self):
        if self.projection_bytes is not None:
            return self.projection_bytes
        from . import compile_obs
        obs = compile_obs.current_observatory()
        proj = getattr(obs, "hbm_projection", None) if obs else None
        return int(proj) if isinstance(proj, (int, float)) and proj > 0 \
            else None

    # -- KV accounting ----------------------------------------------------

    def _kv_fields(self, step):
        if self.kv_source is None:
            return {}
        try:
            kv = self.kv_source()
        except Exception:
            return {}
        if not isinstance(kv, dict):
            return {}
        total = kv.get("blocks_total")
        held = kv.get("blocks_held")
        cached = kv.get("blocks_cached")
        fields = {
            "kv_blocks_total": total,
            "kv_blocks_held": held,
            "kv_blocks_free": kv.get("blocks_free"),
            "kv_blocks_cached": cached,
            "kv_evictions": kv.get("evictions"),
            "kv_admissions": kv.get("admissions"),
            "evictions_by_class": kv.get("evictions_by_class"),
            "admissions_by_class": kv.get("admissions_by_class"),
        }
        if isinstance(total, int) and total > 0:
            if isinstance(held, int) and isinstance(cached, int):
                fields["kv_occupancy"] = min(
                    1.0, (held + cached) / float(total))
            if isinstance(cached, int):
                fields["kv_cache_share"] = min(1.0, cached / float(total))
        # windowed per-step rates from the cumulative counters — written
        # ON the record so offline replay judges the identical numbers.
        # No previous sample -> no window -> no rate (first snapshot is
        # exempt from kv_thrash, not silently rated 0)
        ev, adm = kv.get("evictions"), kv.get("admissions")
        if isinstance(ev, int) and isinstance(adm, int):
            prev = self._prev_kv
            if prev is not None and step > prev[0]:
                dstep = float(step - prev[0])
                fields["kv_eviction_rate"] = max(0, ev - prev[1]) / dstep
                fields["kv_admission_rate"] = max(0, adm - prev[2]) / dstep
            self._prev_kv = (step, ev, adm)
        return {k: v for k, v in fields.items() if v is not None}

    # -- sampling ---------------------------------------------------------

    def snapshot(self, step, device=None):
        """Sample the ledger once into a kind=memsnap record: emit to
        the sink, mirror the mem.* gauges, feed the health detector.
        Returns the record."""
        led = snapshot_ledger(top_k=self.top_k, device=device)
        total = led["total_bytes"]
        budget = self.hbm_budget_bytes
        headroom = max(0, budget - total) if budget else None
        proj = self._projection()
        rec = make_memsnap_record(
            "snapshot", step, total, rank=self.rank,
            params_bytes=led["params_bytes"],
            opt_state_bytes=led["opt_state_bytes"],
            kv_bytes=led["kv_bytes"],
            workspace_bytes=led["workspace_bytes"],
            other_bytes=led["other_bytes"],
            hbm_budget_bytes=budget, headroom_bytes=headroom,
            projected_bytes=proj,
            projection_family=self.projection_family if proj else None,
            n_arrays=led["n_arrays"], engine=self.engine,
            **self._kv_fields(step))
        self._commit(rec)
        monitor.incr("mem.snapshots")
        return rec

    def capture_postmortem(self, error, step=None, device=None):
        """Capture-on-failure: write the forensic record an OOM leaves
        behind — last-known ledger buckets, a FRESH top-K array listing
        (the allocator state at death, not at the last cadence tick),
        the KV pool state, and the active compile-signature families.
        Returns the record."""
        led = snapshot_ledger(top_k=self.top_k, device=device)
        if step is None:
            step = (self.last or {}).get("step", 0) or 0
        total = led["total_bytes"]
        budget = self.hbm_budget_bytes
        top = led["top_arrays"] or [
            {"bytes": 0, "bucket": "other", "note": "no live arrays"}]
        rec = make_memsnap_record(
            "postmortem", step, total, rank=self.rank,
            params_bytes=led["params_bytes"],
            opt_state_bytes=led["opt_state_bytes"],
            kv_bytes=led["kv_bytes"],
            workspace_bytes=led["workspace_bytes"],
            other_bytes=led["other_bytes"],
            hbm_budget_bytes=budget,
            headroom_bytes=max(0, budget - total) if budget else None,
            projected_bytes=self._projection(),
            n_arrays=led["n_arrays"], engine=self.engine,
            error=str(error) or "allocation failure",
            top_arrays=top,
            compile_families=_active_compile_families(),
            **self._kv_fields(step))
        self._commit(rec)
        monitor.incr("mem.postmortems")
        return rec

    def _commit(self, rec):
        self.last = rec
        self.records.append(rec)
        del self.records[:-self.keep]
        if self.sink is not None:
            try:
                self.sink.write(rec)
            except Exception:
                pass
        _export_gauges(rec)
        if self.health is not None:
            try:
                self.health.observe(rec)
            except Exception:
                pass

    # -- the admission signal --------------------------------------------

    def headroom_bytes(self):
        """Bytes between the last sampled total and the declared
        budget (clamped at 0), or None when no budget was declared or
        nothing has been sampled — the serving admission path treats
        None as 'no memory opinion'."""
        if self.last is None:
            return None
        return self.last.get("headroom_bytes")


def _active_compile_families():
    """Summaries of the compile observatory's tracked signature
    families — WHICH compiled programs were resident when the
    allocator failed. [] when no observatory is active."""
    from . import compile_obs
    obs = compile_obs.current_observatory()
    if obs is None:
        return []
    out = []
    try:
        for fam, (sig, count) in sorted(obs.tracker.families.items()):
            row = {"family": str(fam), "n_compiles": int(count)}
            try:
                row.update(sig.summary())
            except Exception:
                pass
            out.append(row)
    except Exception:
        return []
    return out


def _export_gauges(rec):
    """Mirror one ledger record onto /metrics (telemetry.metrics_http
    scrapes monitor.snapshot_typed verbatim)."""
    for key in ("total_bytes", "params_bytes", "opt_state_bytes",
                "kv_bytes", "workspace_bytes", "other_bytes",
                "headroom_bytes", "n_arrays", "kv_occupancy",
                "kv_cache_share"):
        v = rec.get(key)
        if isinstance(v, (int, float)):
            monitor.set_gauge(f"mem.{key}", float(v))


# module-level capture hook: the engine's error path calls this even
# when it never built an observatory — forensics must not depend on a
# budget having been declared
def capture_postmortem(error, sink=None, step=0, rank=0, **kw):
    """One-shot postmortem without a standing observatory."""
    obs = MemoryObservatory(sink=sink, rank=rank, **kw)
    return obs.capture_postmortem(error, step=step)
