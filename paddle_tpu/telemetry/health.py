"""Training health monitor: jit-safe numerics taps + anomaly detection.

The flight recorder (recorder.py) tells you what a step COST and the
graph doctor (paddle_tpu/analysis) rejects programs that are wrong
before dispatch; this module watches a job that is RUNNING WRONG —
NaN'd grads silently poisoning weights, a loss spike three hours in, a
step-time regression after a topology change — and a sibling watchdog
(watchdog.py) catches the job that stops running at all.

Three pieces:

- **Numerics taps** (`device_health_stats`) — global grad-norm,
  update/param ratio, and NaN/Inf counts computed as auxiliary
  DEVICE-SIDE outputs inside the traced train step (TrainStep /
  ShardedTrainStep `health=`). Nothing syncs per step: the step returns
  one extra (5,) f32 array that stays on device; `HealthMonitor`
  fetches it every `every_k` steps (one tiny transfer that doubles as
  the window sync), so `k > 1` adds zero per-step host traffic. Under a
  GSPMD mesh the norms reduce over sharded arrays inside the compiled
  program — the partitioner inserts whatever collectives that needs.

- **Anomaly detector** (`AnomalyDetector`) — rolling-window z-score
  rules over the fetched stats and/or recorded step JSONL: hard NaN/Inf
  (`nan`), `loss_spike`, `grad_explosion`, `step_time_regression`,
  plus `phase_error` for failed bench phases. The same rules run
  in-flight (HealthMonitor) and offline (tools/healthwatch.py replays a
  metrics JSONL), so what pages you in production is exactly what CI
  gates on.

- **HealthMonitor** — ties taps + detector + watchdog together behind
  the `health=` hook: normalizes config, applies the configured action
  (`warn` / `record` / `raise` HealthError), advances the
  `health.anomalies` / `health.nan_steps` monitor counters, exports
  last-seen values as monitor gauges (scraped verbatim by
  `telemetry.metrics_http`), and arms/disarms the hang watchdog around
  each step.

Reference analogs: FLAGS_check_nan_inf (`nan_inf_utils_detail.cc`) is
the hard-stop ancestor of the `nan` rule; the incubate
TensorCheckerConfig ("check_nan_inf + debug mode") is the config-object
shape `HealthConfig` follows; MegaScale/PaLM-style loss-spike skip
logic motivates the rolling-window rules.
"""
import collections
import contextlib
import math
import threading
import time
import warnings

from .. import monitor

__all__ = ["HealthConfig", "HealthError", "Anomaly", "AnomalyDetector",
           "HealthMonitor", "as_monitor", "device_health_stats",
           "HEALTH_STAT_FIELDS"]

# layout of the stacked device stats array (one (5,) f32 per step)
HEALTH_STAT_FIELDS = ("grad_norm", "update_ratio", "nan_count",
                      "inf_count", "loss")

_ACTIONS = ("warn", "record", "raise")


class HealthError(RuntimeError):
    """Raised by action='raise' when an anomaly fires (after counters
    and gauges are advanced, so the crash is still triagable)."""

    def __init__(self, anomalies):
        self.anomalies = list(anomalies)
        super().__init__("; ".join(a.message for a in self.anomalies))


class HealthConfig:
    """Knobs for the in-flight health monitor.

    every_k           fetch the device stats every k-th step (k>1: zero
                      per-step host transfer; the fetch is the only sync)
    action            'warn' (default) | 'record' | 'raise' on anomaly
    window            rolling-window length for the z-score rules
    min_points        points required before a z-rule may fire
    z_loss/z_grad     z-score thresholds for spike/explosion rules
    z_step_time       z threshold for the step-time regression rule
    rel_step_time     AND-guard: step time must also exceed this multiple
                      of the window median (kills micro-jitter flags)
    storm_compiles    recompile-storm rule: this many RECOMPILES (compile
                      records with n_compiles > 1) ...
    storm_window_steps ... within this many steps fire `recompile_storm`
    hbm_drift_tol     relative drift between a compile record's measured
                      hbm.total_bytes and its hbm_projected_bytes (the
                      sharding_lint SH206 projection) that fires
                      `hbm_projection_drift`
    flops_drift_tol   relative drift between a compile record's
                      cost.flops and its analytic_flops (the peak-FLOPs
                      table MFU claims ride on) that fires `flops_drift`
    kernel_drift_tol  multiplicative tolerance between a kernelbench
                      record's measured kernel_ms and its roofline-
                      predicted predicted_ms (telemetry/kernel_obs):
                      `kernel_time_drift` fires when the ratio leaves
                      [1/(1+tol), 1+tol] — symmetric in log space so
                      BOTH directions are reachable (slower: the
                      kernel lost its roofline position; faster than
                      the roofline floor: the KN503 counts the
                      prediction rides on are inflated). Latched per
                      kernel.
    comm_bw_tol       multiplicative tolerance between a commbench
                      record's measured time_ms and its best-known DB
                      latency db_ms (telemetry/comm_obs via
                      tools/comm_db.json): `comm_bw_degraded` fires when
                      measured exceeds (1+tol) x db_ms — ONE-SIDED,
                      faster than the DB is good news the next
                      --update-db rolls in. Latched per op. Records
                      without db_ms (flag off, or no DB row for the
                      key) are exempt: no reference, no jurisdiction.
    straggler_rel     per-rank step-boundary skew rule: a rank whose
                      step_ms exceeds the step's fastest rank by this
                      relative fraction ...
    straggler_abs_ms  ... AND by at least this many absolute ms fires
                      `straggler` (latched per rank; silent when only
                      one rank reports — no skew to judge). A slow rank
                      holds every collective barrier open for the whole
                      mesh, which is why this lives with the comm rules.
    ckpt_stall_s      a kind=ckpt commit record whose save_ms exceeds
                      this many seconds fires `checkpoint_stall`
                      (resilience.CheckpointManager records)
    tail_cause_frac   a kind=reqtrace record whose dominant latency
                      cause is PATHOLOGICAL (queue_wait / preemption /
                      restart / cow_fork — telemetry.reqtrace) with at
                      least this fraction of the request's end-to-end
                      time counts toward `tail_latency`
    tail_cause_count  fire `tail_latency` once this many requests are
                      dominated by the SAME pathological cause (latched
                      per cause: one page per pathology, not per
                      request)
    hbm_pressure_frac memory-observatory records (kind='memsnap',
                      telemetry/mem_obs via tools/memwatch): a ledger
                      whose total_bytes exceeds this fraction of the
                      hbm_budget_bytes riding ON the record fires
                      `hbm_pressure` — ONE-SIDED and latched per
                      engine/rank. Records without a budget are
                      exempt: no budget declared, no jurisdiction.
    kv_thrash_ratio   `kv_thrash` fires when a memsnap record's
                      kv_eviction_rate exceeds this multiple of its
                      kv_admission_rate (evicting faster than the pool
                      admits means the cache is cannibalizing itself)
                      ...
    kv_thrash_min_rate ... AND the eviction rate is at least this many
                      blocks/step — an idle pool evicting a stray
                      block must not page. Latched per engine/rank;
                      records without rates (first snapshot — no
                      window yet) are exempt.
    mem_reconcile_tol multiplicative tolerance between a memsnap
                      record's live total_bytes and the compile
                      observatory's static projected_bytes riding on
                      the record: `mem_projection_drift` fires when the
                      ratio leaves [1/(1+tol), 1+tol] — either side
                      means the static planning numbers no longer
                      describe what the chip actually holds. Latched
                      per projection_family.
    hang_deadline_s   arm a HangWatchdog with this deadline (None: off)
    dump_dir          where black-box dumps go ('.' default)
    dump_on_exception fire the black-box dump when an exception escapes
                      a train step (default True)
    ring_size         last-N step-record ring kept for dumps / /steps
    """

    def __init__(self, every_k=8, action="warn", window=64, min_points=8,
                 z_loss=8.0, z_grad=8.0, z_step_time=8.0,
                 rel_step_time=1.5, storm_compiles=5, storm_window_steps=32,
                 hbm_drift_tol=0.15, flops_drift_tol=0.25,
                 kernel_drift_tol=3.0, comm_bw_tol=1.0,
                 straggler_rel=0.5, straggler_abs_ms=10.0,
                 ckpt_stall_s=300.0, tail_cause_frac=0.6,
                 tail_cause_count=4, hbm_pressure_frac=0.92,
                 kv_thrash_ratio=2.0, kv_thrash_min_rate=1.0,
                 mem_reconcile_tol=0.25, hang_deadline_s=None,
                 dump_dir=".", dump_on_exception=True, ring_size=64):
        if action not in _ACTIONS:
            raise ValueError(f"health action must be one of {_ACTIONS}, "
                             f"got {action!r}")
        if every_k < 1:
            raise ValueError(f"every_k must be >= 1, got {every_k}")
        self.every_k = int(every_k)
        self.action = action
        self.window = int(window)
        self.min_points = int(min_points)
        self.z_loss = float(z_loss)
        self.z_grad = float(z_grad)
        self.z_step_time = float(z_step_time)
        self.rel_step_time = float(rel_step_time)
        self.storm_compiles = int(storm_compiles)
        self.storm_window_steps = int(storm_window_steps)
        self.hbm_drift_tol = float(hbm_drift_tol)
        self.flops_drift_tol = float(flops_drift_tol)
        self.kernel_drift_tol = float(kernel_drift_tol)
        self.comm_bw_tol = float(comm_bw_tol)
        self.straggler_rel = float(straggler_rel)
        self.straggler_abs_ms = float(straggler_abs_ms)
        self.ckpt_stall_s = float(ckpt_stall_s)
        self.tail_cause_frac = float(tail_cause_frac)
        self.tail_cause_count = int(tail_cause_count)
        self.hbm_pressure_frac = float(hbm_pressure_frac)
        self.kv_thrash_ratio = float(kv_thrash_ratio)
        self.kv_thrash_min_rate = float(kv_thrash_min_rate)
        self.mem_reconcile_tol = float(mem_reconcile_tol)
        self.hang_deadline_s = hang_deadline_s
        self.dump_dir = dump_dir
        self.dump_on_exception = bool(dump_on_exception)
        self.ring_size = int(ring_size)

    def __repr__(self):
        return (f"HealthConfig(every_k={self.every_k}, "
                f"action={self.action!r}, window={self.window})")


class Anomaly:
    """One detected anomaly: kind + where + how far out of band."""

    def __init__(self, kind, step, value, message, expected=None, z=None):
        self.kind = kind
        self.step = step
        self.value = value
        self.message = message
        self.expected = expected
        self.z = z

    def to_dict(self):
        d = {"kind": self.kind, "step": self.step,
             "value": _json_safe(self.value), "message": self.message}
        if self.expected is not None:
            d["expected"] = _json_safe(self.expected)
        if self.z is not None:
            d["z"] = _json_safe(self.z)
        return d

    def __repr__(self):
        return f"Anomaly({self.kind} @ step {self.step}: {self.message})"


def _json_safe(v):
    if isinstance(v, float) and not math.isfinite(v):
        return repr(v)
    return v


def _finite(v):
    return isinstance(v, (int, float)) and math.isfinite(v)


class _Window:
    """Rolling mean/std/median window with a relative std floor (a
    near-constant series must not turn noise into infinite z-scores)."""

    def __init__(self, size):
        self._buf = collections.deque(maxlen=size)

    def __len__(self):
        return len(self._buf)

    def add(self, v):
        self._buf.append(float(v))

    def stats(self):
        n = len(self._buf)
        mean = sum(self._buf) / n
        var = sum((v - mean) ** 2 for v in self._buf) / n
        std = max(math.sqrt(var), abs(mean) * 0.01, 1e-9)
        med = sorted(self._buf)[n // 2]
        return mean, std, med

    def z(self, v):
        mean, std, _ = self.stats()
        return (v - mean) / std


class AnomalyDetector:
    """Stateful rule engine over a stream of step records.

    `observe(record)` takes one step-record dict (the JSONL schema, or
    the partial dict HealthMonitor assembles in flight — only keys that
    are present are judged) and returns the anomalies it triggered.
    Rules:

    - nan                  nan_count/inf_count > 0, or a non-finite
                           loss/grad_norm/update_ratio value
    - loss_spike           loss z-score above z_loss vs the rolling
                           window (upward only — a falling loss is the
                           point of training)
    - grad_explosion       grad_norm z-score above z_grad (upward)
    - step_time_regression step time z above z_step_time AND above
                           rel_step_time x window median; records with
                           compile_ms > 0 are exempt (recompiles are
                           legitimately slow) and never enter the window
    - phase_error          a bench phase record carrying an 'error' key
                           or a non-finite metric value
    - recompile_storm      compile records (kind='compile',
                           telemetry.compile_obs): storm_compiles
                           RECOMPILES (n_compiles > 1 — first compiles
                           of distinct programs are legitimate) within
                           storm_window_steps steps
    - hbm_projection_drift a compile record whose measured
                           hbm.total_bytes drifts more than
                           hbm_drift_tol from its hbm_projected_bytes
                           (the sharding_lint SH206 static projection)
    - flops_drift          a compile record whose cost.flops drifts more
                           than flops_drift_tol from its analytic_flops
                           (the MFU peak-FLOPs accounting)
    - checkpoint_failed    a ckpt record (kind='ckpt', resilience
                           runtime) with event='failed' (retries
                           exhausted) or event='fallback' (a corrupt
                           checkpoint was skipped at restore)
    - checkpoint_stall     a ckpt commit whose save_ms exceeds
                           ckpt_stall_s — saves that slow eat the
                           preemption grace window
    - comm_bw_degraded     mesh-observatory records (kind='commbench',
                           telemetry/comm_obs via tools/commlab): a
                           measured collective more than (1+comm_bw_tol)x
                           SLOWER than its best-known DB latency db_ms.
                           One-sided + latched per op; records without
                           db_ms are exempt (no DB reference riding the
                           record — flag off or no row for the key)
    - straggler            per-rank step-boundary skew over step records
                           from >= 2 ranks: a rank whose step_ms exceeds
                           the step's fastest rank by straggler_rel AND
                           straggler_abs_ms — it is holding every
                           collective barrier open for the mesh. Latched
                           per rank; compile steps exempt (a recompiling
                           rank is legitimately slow); silent with one
                           rank reporting
    - tail_latency         request-trace records (kind='reqtrace',
                           telemetry.reqtrace): tail_cause_count
                           requests dominated (>= tail_cause_frac of
                           their end-to-end latency) by the same
                           PATHOLOGICAL cause — queue_wait, preemption,
                           restart, or cow_fork; decode/prefill
                           dominating is the work the user asked for.
                           Latched per cause so one pathology pages
                           once, not once per request
    - hbm_pressure         memory-observatory records (kind='memsnap',
                           telemetry/mem_obs via tools/memwatch): a
                           live ledger whose total_bytes exceeds
                           hbm_pressure_frac of the hbm_budget_bytes
                           riding ON the record. One-sided + latched
                           per engine/rank; records without a budget
                           are exempt (none declared, no jurisdiction)
    - kv_thrash            a memsnap record whose kv_eviction_rate
                           exceeds kv_thrash_ratio x its
                           kv_admission_rate AND kv_thrash_min_rate
                           blocks/step — the KV pool is evicting
                           faster than it admits (the cache is
                           cannibalizing itself to feed churn).
                           Latched per engine/rank; first snapshots
                           (no rate window yet) are exempt
    - mem_projection_drift a memsnap record whose live total_bytes
                           leaves the [1/(1+mem_reconcile_tol),
                           1+mem_reconcile_tol] band around the compile
                           observatory's static projected_bytes —
                           either side means the planning numbers no
                           longer describe the chip. Latched per
                           projection_family

    Clean values enter their windows AFTER judgment, so a spike does not
    vaccinate the window against itself; anomalous values are excluded
    from the windows entirely.
    """

    def __init__(self, config=None):
        self.config = config or HealthConfig()
        c = self.config
        self._loss = _Window(c.window)
        self._grad = _Window(c.window)
        self._step_t = _Window(c.window)
        self._recompiles = {}         # fn -> deque of (step, cause)
        self._storm_muzzle = {}       # fn -> muzzled-until step
        self._drift_latched = set()   # (kind, fn) already flagged
        self._tail_counts = {}        # cause -> dominated-request count
        self._tail_latched = set()    # causes already paged
        self._step_ranks = {}         # step -> {rank: step_ms} (skew)
        self.anomalies = []
        self._n = 0

    # -- helpers ------------------------------------------------------------
    def _z_rule(self, win, value, z_thresh, step, kind, label,
                rel_guard=None):
        if not _finite(value):
            return None
        fired = None
        if len(win) >= self.config.min_points:
            mean, std, med = win.stats()
            z = (value - mean) / std
            rel_ok = True if rel_guard is None else \
                value > rel_guard * max(med, 1e-9)
            if z > z_thresh and rel_ok:
                fired = Anomaly(
                    kind, step, value,
                    f"{label} {value:.6g} is {z:.1f} sigma above the "
                    f"rolling mean {mean:.6g} (window {len(win)})",
                    expected=mean, z=round(z, 2))
        if fired is None:
            win.add(value)
        return fired

    # -- the rule pass ------------------------------------------------------
    def observe(self, record):
        """Judge one record; returns [Anomaly, ...] ([] == healthy)."""
        self._n += 1
        rec = record or {}
        if rec.get("kind") == "phase":
            found = self._observe_phase(rec)
            self.anomalies.extend(found)
            return found
        if rec.get("kind") == "compile":
            found = self._observe_compile(rec)
            self.anomalies.extend(found)
            return found
        if rec.get("kind") == "ckpt":
            found = self._observe_ckpt(rec)
            self.anomalies.extend(found)
            return found
        if rec.get("kind") == "reqtrace":
            found = self._observe_reqtrace(rec)
            self.anomalies.extend(found)
            return found
        if rec.get("kind") == "kernelbench":
            found = self._observe_kernelbench(rec)
            self.anomalies.extend(found)
            return found
        if rec.get("kind") == "commbench":
            found = self._observe_commbench(rec)
            self.anomalies.extend(found)
            return found
        if rec.get("kind") == "memsnap":
            found = self._observe_memsnap(rec)
            self.anomalies.extend(found)
            return found
        step = rec.get("step", self._n - 1)
        found = []

        # straggler first: per-rank skew is judged on the raw step
        # boundary, independently of what the z-rules think of the
        # value; compile steps are exempt like step_time_regression
        # (a recompiling rank is legitimately slow)
        if _finite(rec.get("step_ms")) and rec.get("rank") is not None \
                and not rec.get("compile_ms"):
            found.extend(self._observe_straggler(
                step, int(rec["rank"]), float(rec["step_ms"])))

        # hard NaN/Inf first: a poisoned step must not feed the windows
        nan_n = rec.get("nan_count") or 0
        inf_n = rec.get("inf_count") or 0
        bad_vals = [k for k in ("loss", "grad_norm", "update_ratio")
                    if isinstance(rec.get(k), float)
                    and not math.isfinite(rec[k])]
        if nan_n or inf_n or bad_vals:
            parts = []
            if nan_n:
                parts.append(f"{int(nan_n)} NaN value(s)")
            if inf_n:
                parts.append(f"{int(inf_n)} Inf value(s)")
            if bad_vals:
                parts.append("non-finite " + "/".join(bad_vals))
            found.append(Anomaly(
                "nan", step, float(nan_n + inf_n) or float("nan"),
                f"step {step}: " + ", ".join(parts)
                + " in loss/grads — updates from this step are suspect"))
            self.anomalies.extend(found)
            return found   # no window feeding, no further rules

        a = self._z_rule(self._loss, rec.get("loss"),
                         self.config.z_loss, step, "loss_spike", "loss")
        if a:
            found.append(a)
        a = self._z_rule(self._grad, rec.get("grad_norm"),
                         self.config.z_grad, step, "grad_explosion",
                         "grad norm")
        if a:
            found.append(a)

        st = rec.get("step_time_ms")
        if st is None:
            st = rec.get("execute_ms")
        if st is None:
            st = rec.get("step_ms")
        if st is not None and not rec.get("compile_ms"):
            a = self._z_rule(self._step_t, st, self.config.z_step_time,
                             step, "step_time_regression", "step time (ms)",
                             rel_guard=self.config.rel_step_time)
            if a:
                found.append(a)
        self.anomalies.extend(found)
        return found

    def _observe_phase(self, rec):
        name = rec.get("phase", "?")
        found = []
        metrics = rec.get("metrics") or {}
        if "error" in metrics or "error" in rec:
            found.append(Anomaly(
                "phase_error", name, None,
                f"phase {name!r} recorded an error: "
                f"{metrics.get('error') or rec.get('error')}"))
        bad = [k for k, v in metrics.items()
               if isinstance(v, float) and not math.isfinite(v)]
        if bad:
            found.append(Anomaly(
                "phase_error", name, None,
                f"phase {name!r} carries non-finite metric(s): {bad}"))
        return found

    def _observe_compile(self, rec):
        """Rules over one compile-event record (telemetry.compile_obs):
        the storm window plus the two static-vs-compiled cross-checks.
        The record carries everything the rules need (measured AND
        projected/analytic values), so the same pass runs in-flight and
        in offline replays (tools/compile_report.py)."""
        c = self.config
        found = []
        step = rec.get("step", self._n - 1)
        fn = rec.get("fn", "?")

        # recompile storm: only RECOMPILES count — the first compile of
        # each distinct program (and untracked jax-stream events, which
        # cannot tell first from Nth) is legitimate work, not thrash.
        # Windows and muzzles are PER FAMILY: a planned bump that
        # recompiles several distinct programs at once is not a storm,
        # and one family's storm must not silence another's.
        if not rec.get("untracked") and rec.get("n_compiles", 1) > 1:
            win = self._recompiles.get(fn)
            if win is None:
                win = self._recompiles[fn] = collections.deque(
                    maxlen=c.storm_compiles)
            win.append((step, rec.get("cause")))
            span = step - win[0][0]
            muzzled = step <= self._storm_muzzle.get(fn, -1)
            if (len(win) >= c.storm_compiles
                    and span <= c.storm_window_steps and not muzzled):
                causes = [cc for _, cause in win for cc in (cause or [])]
                hint = f"; last cause: {causes[-1]}" if causes else ""
                found.append(Anomaly(
                    "recompile_storm", step, float(len(win)),
                    f"{fn}: {len(win)} recompiles within "
                    f"{span} step(s) (threshold {c.storm_compiles} in "
                    f"{c.storm_window_steps}){hint}",
                    expected=c.storm_compiles))
                self._storm_muzzle[fn] = step + c.storm_window_steps

        # drift rules are LATCHED per family: a drifting program fires
        # once (it recompiles many times in a storm — one page, not N),
        # and re-arms only after a compile comes back inside tolerance
        hbm = rec.get("hbm") or {}
        actual = hbm.get("total_bytes")
        projected = rec.get("hbm_projected_bytes")
        if actual and projected:
            drift = (float(actual) - float(projected)) / float(projected)
            if abs(drift) <= c.hbm_drift_tol:
                self._drift_latched.discard(("hbm_projection_drift", fn))
            elif ("hbm_projection_drift", fn) not in self._drift_latched:
                self._drift_latched.add(("hbm_projection_drift", fn))
                found.append(Anomaly(
                    "hbm_projection_drift", step, float(actual),
                    f"{fn}: compiled HBM {actual / 1e6:.2f} MB is "
                    f"{drift * 100:+.0f}% off the static projection "
                    f"{projected / 1e6:.2f} MB (tolerance "
                    f"{c.hbm_drift_tol * 100:.0f}%) — the SH206 "
                    "pre-flight budget no longer describes this program",
                    expected=projected, z=round(drift, 3)))

        compiled_flops = (rec.get("cost") or {}).get("flops")
        analytic = rec.get("analytic_flops")
        from .mfu import flops_drift
        drift = flops_drift(compiled_flops, analytic)
        if drift is not None:
            if abs(drift) <= c.flops_drift_tol:
                self._drift_latched.discard(("flops_drift", fn))
            elif ("flops_drift", fn) not in self._drift_latched:
                self._drift_latched.add(("flops_drift", fn))
                found.append(Anomaly(
                    "flops_drift", step, float(compiled_flops),
                    f"{fn}: compiled FLOPs {float(compiled_flops):.3e} "
                    f"drift {drift * 100:+.0f}% from the analytic "
                    f"{float(analytic):.3e} the MFU accounting assumes "
                    f"(tolerance {c.flops_drift_tol * 100:.0f}%)",
                    expected=analytic, z=round(drift, 3)))
        return found

    def _observe_kernelbench(self, rec):
        """The kernel_time_drift rule over one kernel-observatory
        measurement record (telemetry/kernel_obs via tools/kernellab):
        measured kernel_ms vs the roofline-predicted predicted_ms,
        latched per kernel like the compile drift rules — a drifting
        kernel fires once (a sweep measures it at many shapes — one
        page, not N) and re-arms only after a measurement comes back
        inside tolerance. Records without predicted_ms (CPU backends,
        where the peak tables answer None) are exempt: no roofline, no
        drift to judge. Same records in flight and offline
        (tools/healthwatch.py, kernellab --selfcheck), so replays
        agree."""
        c = self.config
        found = []
        kernel = rec.get("kernel", "?")
        measured = rec.get("kernel_ms")
        predicted = rec.get("predicted_ms")
        if not isinstance(measured, (int, float)) or measured <= 0 \
                or not isinstance(predicted, (int, float)) \
                or predicted <= 0:
            return found
        # Multiplicative band: relative drift is bounded below by -1,
        # so a subtractive |drift| > tol test with tol >= 1 could NEVER
        # fire in the too-fast direction. The ratio test is symmetric
        # in log space and both sides stay reachable at any tolerance.
        ratio = float(measured) / float(predicted)
        band = 1.0 + c.kernel_drift_tol
        if 1.0 / band <= ratio <= band:
            self._drift_latched.discard(("kernel_time_drift", kernel))
        elif ("kernel_time_drift", kernel) not in self._drift_latched:
            self._drift_latched.add(("kernel_time_drift", kernel))
            if ratio > band:
                side = (f"{ratio:.1f}x slower than")
            else:
                side = (f"{1.0 / ratio:.1f}x faster than")
            found.append(Anomaly(
                "kernel_time_drift", rec.get("step", self._n - 1),
                float(measured),
                f"{kernel}: measured {float(measured):.3f} ms is "
                f"{side} the roofline-predicted "
                f"{float(predicted):.3f} ms (band {1.0 / band:.2f}x"
                f"–{band:.2f}x) — the KN503 counts or the peak tables "
                "no longer describe this kernel",
                expected=predicted, z=round(ratio, 3)))
        return found

    def _observe_commbench(self, rec):
        """The comm_bw_degraded rule over one mesh-observatory
        measurement record (telemetry/comm_obs via tools/commlab):
        measured time_ms vs the best-known DB latency db_ms riding ON
        the record — the same reference in flight and in offline replay
        (tools/healthwatch.py, commlab --selfcheck), so they agree.
        ONE-SIDED: only slower-than-(1+comm_bw_tol)x-the-DB fires;
        faster is good news the next --update-db rolls into the DB.
        Latched per op (a sweep measures one op at many payloads — one
        page, not N) and re-armed by an in-band measurement. Records
        without db_ms (PADDLE_TPU_COMM_DB off, or no row for this key)
        are exempt: no reference, no jurisdiction."""
        c = self.config
        found = []
        op = rec.get("op", "?")
        measured = rec.get("time_ms")
        reference = rec.get("db_ms")
        if not isinstance(measured, (int, float)) or measured <= 0 \
                or not isinstance(reference, (int, float)) \
                or reference <= 0:
            return found
        ratio = float(measured) / float(reference)
        band = 1.0 + c.comm_bw_tol
        if ratio <= band:
            self._drift_latched.discard(("comm_bw_degraded", op))
        elif ("comm_bw_degraded", op) not in self._drift_latched:
            self._drift_latched.add(("comm_bw_degraded", op))
            found.append(Anomaly(
                "comm_bw_degraded", rec.get("step", self._n - 1),
                float(measured),
                f"{op} over axis {rec.get('axis', '?')!r} "
                f"(n={rec.get('axis_size', '?')}, "
                f"{rec.get('payload_bytes', '?')} B): measured "
                f"{float(measured):.3f} ms is {ratio:.1f}x slower than "
                f"the best-known {float(reference):.3f} ms "
                f"(band {band:.2f}x) — an ICI link or a peer is "
                "degraded, or the DB row no longer describes this mesh",
                expected=reference, z=round(ratio, 3)))
        return found

    def _observe_memsnap(self, rec):
        """The hbm_pressure / kv_thrash / mem_projection_drift rules
        over one memory-observatory ledger record (kind='memsnap',
        telemetry/mem_obs via tools/memwatch): every reference judged
        against — the declared budget, the eviction/admission rates,
        the static projection — rides ON the record, so the in-flight
        detector and offline replay (tools/healthwatch.py, memwatch
        --selfcheck) see identical numbers. Records without a
        reference are exempt per rule: no budget -> no pressure
        jurisdiction, no rate window yet -> no thrash jurisdiction, no
        projection -> no drift jurisdiction (the commbench stance).
        All three latch: pressure/thrash per engine (falling back to
        rank), drift per projection_family."""
        c = self.config
        found = []
        step = rec.get("step", self._n - 1)
        engine = rec.get("engine")
        fam = f"engine{engine}" if engine is not None \
            else f"rank{rec.get('rank', 0)}"
        total = rec.get("total_bytes")
        budget = rec.get("hbm_budget_bytes")
        if isinstance(total, (int, float)) and total >= 0 \
                and isinstance(budget, (int, float)) and budget > 0:
            frac = float(total) / float(budget)
            key = ("hbm_pressure", fam)
            if frac <= c.hbm_pressure_frac:
                self._drift_latched.discard(key)
            elif key not in self._drift_latched:
                self._drift_latched.add(key)
                found.append(Anomaly(
                    "hbm_pressure", step, float(total),
                    f"{fam}: live HBM ledger holds "
                    f"{float(total) / 2**20:.1f} MiB — "
                    f"{frac * 100:.0f}% of the declared "
                    f"{float(budget) / 2**20:.1f} MiB budget (band "
                    f"{c.hbm_pressure_frac * 100:.0f}%) — the next "
                    "allocation spike is an OOM, shed load or raise "
                    "the budget",
                    expected=budget, z=round(frac, 3)))
        ev = rec.get("kv_eviction_rate")
        adm = rec.get("kv_admission_rate")
        if isinstance(ev, (int, float)) and ev >= 0 \
                and isinstance(adm, (int, float)) and adm >= 0:
            key = ("kv_thrash", fam)
            thrash = ev >= c.kv_thrash_min_rate \
                and ev > c.kv_thrash_ratio * adm
            if not thrash:
                self._drift_latched.discard(key)
            elif key not in self._drift_latched:
                self._drift_latched.add(key)
                found.append(Anomaly(
                    "kv_thrash", step, float(ev),
                    f"{fam}: KV pool evicting {float(ev):.2f} "
                    f"blocks/step against {float(adm):.2f} "
                    f"admitted/step (ratio threshold "
                    f"{c.kv_thrash_ratio:.1f}x, floor "
                    f"{c.kv_thrash_min_rate:.1f}/step) — the cache is "
                    "cannibalizing itself to feed churn; admission is "
                    "outrunning the block budget",
                    expected=adm, z=round(ev / max(adm, 1e-9), 3)))
        proj = rec.get("projected_bytes")
        pfam = rec.get("projection_family", "default")
        if isinstance(total, (int, float)) and total > 0 \
                and isinstance(proj, (int, float)) and proj > 0:
            ratio = float(total) / float(proj)
            band = 1.0 + c.mem_reconcile_tol
            key = ("mem_projection_drift", pfam)
            if 1.0 / band <= ratio <= band:
                self._drift_latched.discard(key)
            elif key not in self._drift_latched:
                self._drift_latched.add(key)
                side = f"{ratio:.2f}x above" if ratio > band \
                    else f"{1.0 / ratio:.2f}x below"
                found.append(Anomaly(
                    "mem_projection_drift", step, float(total),
                    f"{pfam}: live ledger total "
                    f"{float(total) / 2**20:.1f} MiB is {side} the "
                    f"static projection "
                    f"{float(proj) / 2**20:.1f} MiB (band "
                    f"{1.0 / band:.2f}x–{band:.2f}x) — the compile "
                    "observatory's planning numbers no longer "
                    "describe what the chip holds",
                    expected=proj, z=round(ratio, 3)))
        return found

    def _observe_straggler(self, step, rank, step_ms):
        """Per-rank step-boundary skew: collect step_ms by rank per
        step, judge every rank of the step against its fastest — a rank
        persistently past BOTH the relative and absolute bands is
        holding every collective barrier open for the whole mesh.
        Latched per rank (one page per straggling host, not one per
        step) and re-armed when the rank comes back in band. With one
        rank reporting there is no skew to judge — silent."""
        c = self.config
        ranks = self._step_ranks.setdefault(step, {})
        ranks[rank] = step_ms
        # settle old steps: ranks report a step at most a few steps
        # apart (the skew being measured IS that gap), so anything 8+
        # steps behind the newest is closed bookkeeping
        if len(self._step_ranks) > 8:
            for s in [s for s in self._step_ranks if s < step - 8]:
                del self._step_ranks[s]
        found = []
        if len(ranks) < 2:
            return found
        fastest = min(ranks.values())
        for r, ms in sorted(ranks.items()):
            slow = ms > fastest * (1.0 + c.straggler_rel) \
                and (ms - fastest) >= c.straggler_abs_ms
            if not slow:
                self._drift_latched.discard(("straggler", r))
            elif ("straggler", r) not in self._drift_latched:
                self._drift_latched.add(("straggler", r))
                found.append(Anomaly(
                    "straggler", step, float(ms),
                    f"rank {r}: step {step} took {ms:.1f} ms vs the "
                    f"fastest rank's {fastest:.1f} ms "
                    f"(+{ms - fastest:.1f} ms; threshold "
                    f"+{c.straggler_rel * 100:.0f}% and >= "
                    f"{c.straggler_abs_ms:.0f} ms) — every collective "
                    "barrier waits for this rank",
                    expected=fastest,
                    z=round(ms / max(fastest, 1e-9), 3)))
        return found

    def _observe_ckpt(self, rec):
        """Rules over one checkpoint-event record (kind='ckpt',
        paddle_tpu.resilience): failed saves/restores and corrupt-
        checkpoint fallbacks page as `checkpoint_failed`; a commit
        slower than ckpt_stall_s pages as `checkpoint_stall` (the
        preemption grace window is the budget a save must fit). Same
        records in flight (CheckpointManager health=) and offline
        (tools/healthwatch.py), so replays agree."""
        found = []
        step = rec.get("step", self._n - 1)
        event = rec.get("event")
        if event == "failed":
            found.append(Anomaly(
                "checkpoint_failed", step, None,
                f"step {step}: checkpoint {rec.get('op', 'operation')} "
                f"failed permanently: {rec.get('error', 'unknown error')}"))
        elif event == "fallback":
            probs = rec.get("problems") or []
            hint = f" ({probs[0]})" if probs else ""
            found.append(Anomaly(
                "checkpoint_failed", step, None,
                f"checkpoint at step {step} failed integrity "
                f"verification{hint}; restore fell back to an older one"))
        elif event == "commit":
            save_ms = rec.get("save_ms")
            limit_ms = self.config.ckpt_stall_s * 1000.0
            if _finite(save_ms) and save_ms > limit_ms:
                found.append(Anomaly(
                    "checkpoint_stall", step, float(save_ms),
                    f"step {step}: checkpoint save took "
                    f"{save_ms / 1000.0:.1f}s (budget "
                    f"{self.config.ckpt_stall_s:.0f}s) — a preemption "
                    "during a save this slow loses the step",
                    expected=limit_ms))
        return found

    def _observe_reqtrace(self, rec):
        """The tail-latency rule over one request-trace record
        (kind='reqtrace', telemetry.reqtrace): requests whose latency
        is DOMINATED by a serving mechanism (queue wait, preemption,
        warm restart, CoW forking) rather than by the prefill/decode
        work they asked for are counted per cause; past
        tail_cause_count the cause pages once (latched). Same records
        in flight (the engine's sink) and offline (tools/healthwatch.py
        + tools/tail_report.py), so replays agree with production."""
        from .reqtrace import PATHOLOGICAL_CAUSES, dominant_cause

        c = self.config
        cause, ms, frac = dominant_cause(rec)
        if cause not in PATHOLOGICAL_CAUSES or frac < c.tail_cause_frac:
            return []
        n = self._tail_counts.get(cause, 0) + 1
        self._tail_counts[cause] = n
        if n < c.tail_cause_count or cause in self._tail_latched:
            return []
        self._tail_latched.add(cause)
        return [Anomaly(
            "tail_latency", rec.get("rid", self._n - 1), float(ms),
            f"{n} request(s) dominated by {cause} (latest: request "
            f"{rec.get('rid')} spent {ms:.1f}ms / {frac * 100:.0f}% of "
            f"its {rec.get('e2e_ms')}ms end-to-end in {cause}; "
            f"threshold {c.tail_cause_count} requests at "
            f">={c.tail_cause_frac * 100:.0f}%)",
            expected=c.tail_cause_frac, z=round(frac, 3))]

    def kinds(self):
        """Distinct anomaly kinds seen so far (healthwatch --expect)."""
        return sorted({a.kind for a in self.anomalies})


class HealthMonitor:
    """In-flight glue: taps -> detector -> action, plus the watchdog.

    A train step with `health=` brackets its body with `guard()`:

        with mon.guard(window) as g:     # arms the hang watchdog
            out = dispatch(...)          # raise -> black-box dump
            g.stage(stats_dev)           # device stats, still lazy
        # on success guard ran step_close: disarm + fetch every k +
        # note the fetched fields into the telemetry step window

    `stats_dev` is the device-side (5,) array from
    `device_health_stats` (or None for record-only integrations, e.g.
    the hapi callback, which passes host values via `loss=`).
    `step_close` returns None on non-fetch steps, else the dict of
    health fields merged into the step's JSONL record; the watchdog is
    disarmed even when action='raise' turns an anomaly into a
    HealthError mid-close.
    """

    def __init__(self, config=None):
        if isinstance(config, dict):
            config = HealthConfig(**config)
        self.config = config or HealthConfig()
        self.detector = AnomalyDetector(self.config)
        self.ring = collections.deque(maxlen=self.config.ring_size)
        self.watchdog = None
        self._wd_started = False
        self._mu = threading.Lock()
        self._step = 0
        self._pending = None          # latest un-fetched device stats
        self._staged = None           # stats handed over via guard/stage
        self._t_last_fetch = None
        self._steps_since_fetch = 0
        if self.config.hang_deadline_s:
            from .watchdog import HangWatchdog
            self.watchdog = HangWatchdog(
                deadline_s=self.config.hang_deadline_s,
                dump_dir=self.config.dump_dir, ring=self.ring)

    # -- step lifecycle -----------------------------------------------------
    @contextlib.contextmanager
    def guard(self, window=None):
        """Bracket one train step. Arms the watchdog; an escaping
        exception triggers the black-box dump (then re-raises); on
        success runs step_close with whatever the body `stage()`d and
        notes the fetched fields into `window` (a telemetry step
        window with .note, e.g. from auto_step). The single wrapper
        shared by TrainStep / ShardedTrainStep / PipelineParallel."""
        self.step_open()
        try:
            yield self
        except Exception as e:
            self.on_exception(e)
            raise
        else:
            stats, self._staged = self._staged, None
            fields = self.step_close(stats)
            if fields and window is not None:
                window.note(**fields)

    def stage(self, stats_dev):
        """Hand the step's device-side stats array to the enclosing
        guard() (kept lazy; fetched on the every_k cadence)."""
        self._staged = stats_dev

    def will_fetch(self):
        """True when the NEXT step_close will fetch+judge — lets eager
        (non-jit) integrations skip building tap values that would
        only be discarded on non-fetch steps."""
        return self._steps_since_fetch + 1 >= self.config.every_k

    def step_open(self):
        if self.watchdog is not None:
            if not self._wd_started:
                self.watchdog.start()
                self._wd_started = True
            self.watchdog.step_opened()

    def step_close(self, stats_dev=None, loss=None, step_ms=None):
        """Close one step. Fetches + judges every `every_k`-th call;
        otherwise just rotates the pending device handle (no sync).
        The watchdog is disarmed even when action='raise' escalates an
        anomaly to HealthError out of the judge."""
        self._step += 1
        self._steps_since_fetch += 1
        fields = None
        if stats_dev is not None:
            self._pending = stats_dev
        if self._pending is not None:
            # device stats pending: honor the every_k fetch cadence (the
            # fetch is the only host transfer the taps ever make)
            fetch = self._steps_since_fetch >= self.config.every_k
        else:
            # record-level integration (host values only): judging is
            # free, so every step goes through the rules
            fetch = loss is not None or step_ms is not None
        try:
            if fetch:
                fields = self._fetch_and_judge(loss=loss, step_ms=step_ms)
        finally:
            if self.watchdog is not None:
                # ring is shared with the watchdog, so no record= here —
                # _fetch_and_judge already appended the full record
                self.watchdog.step_closed()
        return fields

    def observe_record(self, record):
        """Record-level entry (hapi callback / offline replay through a
        live monitor): judge a full step-record dict immediately."""
        self.ring.append(record)
        found = self.detector.observe(record)
        if found:
            self._act(found)
        return found

    def on_exception(self, exc):
        """An exception escaped the train step: count it, dump the
        black box (same dump the hang watchdog writes), disarm."""
        monitor.incr("health.step_exceptions")
        path = None
        if self.config.dump_on_exception:
            from . import watchdog as _wd
            path = _wd.dump_black_box(
                reason=f"exception escaped train step: "
                       f"{type(exc).__name__}: {exc}",
                dump_dir=self.config.dump_dir, ring=list(self.ring))
        if self.watchdog is not None:
            self.watchdog.step_closed()
        return path

    def close(self):
        if self.watchdog is not None and self._wd_started:
            self.watchdog.stop()
            self._wd_started = False

    # -- internals ----------------------------------------------------------
    def _fetch_and_judge(self, loss=None, step_ms=None):
        import numpy as np
        now = time.perf_counter()
        fields = {}
        if self._pending is not None:
            vals = np.asarray(self._pending)   # the every-k host transfer
            self._pending = None
            monitor.incr("health.fetches")
            fields = {
                "grad_norm": float(vals[0]),
                "update_ratio": float(vals[1]),
                "nan_count": int(vals[2]) if math.isfinite(
                    float(vals[2])) else 1,
                "inf_count": int(vals[3]) if math.isfinite(
                    float(vals[3])) else 1,
            }
            if loss is None:
                loss = float(vals[4])
        rec = dict(fields)
        rec["step"] = self._step - 1
        if loss is not None:
            rec["loss"] = float(loss)
            fields["loss"] = float(loss)
        if step_ms is not None:
            rec["step_time_ms"] = float(step_ms)
        elif self._t_last_fetch is not None:
            # the fetch synced the device, so wall time since the LAST
            # fetch covers every step in the window; the average is an
            # honest per-step time with zero extra syncs. The first
            # window is skipped (it pays compile).
            rec["step_time_ms"] = ((now - self._t_last_fetch) * 1000.0
                                   / max(1, self._steps_since_fetch))
        self._t_last_fetch = now
        self._steps_since_fetch = 0

        for k, v in fields.items():
            if isinstance(v, (int, float)) and math.isfinite(float(v)):
                monitor.set_gauge(f"health.{k}", float(v))
        self.ring.append(rec)
        found = self.detector.observe(rec)
        if found:
            self._act(found)
        # loss rode along only for the detector; the recorder already
        # owns the loss field of the JSONL record
        fields.pop("loss", None)
        return fields or None

    def _act(self, anomalies):
        monitor.incr("health.anomalies", len(anomalies))
        nan_hits = [a for a in anomalies if a.kind == "nan"]
        if nan_hits:
            monitor.incr("health.nan_steps", len(nan_hits))
        if self.config.action == "record":
            return
        if self.config.action == "warn":
            for a in anomalies:
                warnings.warn(f"[health] {a.message}", RuntimeWarning,
                              stacklevel=3)
            return
        raise HealthError(anomalies)

    @property
    def anomalies(self):
        return self.detector.anomalies


def as_monitor(health):
    """Normalize the `health=` argument of TrainStep/ShardedTrainStep/
    PipelineParallel: None/False -> None, True -> default HealthMonitor,
    dict/HealthConfig -> wrapped, HealthMonitor -> itself (shared across
    steps so the windows/watchdog are one per job)."""
    if health is None or health is False:
        return None
    if isinstance(health, HealthMonitor):
        return health
    if health is True:
        return HealthMonitor()
    if isinstance(health, (dict, HealthConfig)):
        return HealthMonitor(health)
    raise TypeError(
        f"health= expects True/dict/HealthConfig/HealthMonitor, "
        f"got {type(health).__name__}")


# ---------------------------------------------------------------------------
# device-side taps (called INSIDE the traced step — jnp only, no host)
# ---------------------------------------------------------------------------

def device_health_stats(loss_val, grads, new_vals, param_vals):
    """Build the (5,) f32 health stats array inside a traced train step:
    [global grad-norm, update/param norm ratio, NaN count, Inf count,
    loss]. Pure jnp on tracers — no `.item()`, no `device_get`, no
    callbacks — so it fuses into the step's XLA program and costs a few
    tiny reductions; under GSPMD the partitioner lowers the norms over
    sharded arrays with its own collectives."""
    import jax.numpy as jnp

    f32 = jnp.float32
    if grads:
        sq = [jnp.sum(jnp.square(g.astype(f32))) for g in grads]
        grad_norm = jnp.sqrt(jnp.stack(sq).sum())
        nan_count = jnp.stack(
            [jnp.sum(jnp.isnan(g)) for g in grads]).sum()
        inf_count = jnp.stack(
            [jnp.sum(jnp.isinf(g)) for g in grads]).sum()
    else:
        grad_norm = jnp.zeros((), f32)
        nan_count = jnp.zeros((), jnp.int32)
        inf_count = jnp.zeros((), jnp.int32)
    nan_count = nan_count + jnp.sum(jnp.isnan(loss_val))
    inf_count = inf_count + jnp.sum(jnp.isinf(loss_val))

    if new_vals and param_vals:
        upd_sq = [jnp.sum(jnp.square(n.astype(f32) - o.astype(f32)))
                  for n, o in zip(new_vals, param_vals)]
        par_sq = [jnp.sum(jnp.square(o.astype(f32))) for o in param_vals]
        upd = jnp.sqrt(jnp.stack(upd_sq).sum())
        par = jnp.sqrt(jnp.stack(par_sq).sum())
        update_ratio = upd / jnp.maximum(par, 1e-12)
    else:
        update_ratio = jnp.zeros((), f32)

    return jnp.stack([grad_norm.astype(f32), update_ratio.astype(f32),
                      nan_count.astype(f32), inf_count.astype(f32),
                      jnp.asarray(loss_val, f32).reshape(())])
