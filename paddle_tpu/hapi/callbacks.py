"""Training callbacks (reference `python/paddle/hapi/callbacks.py`:
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL)."""
import os
import sys
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks or [])
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)
        return call


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        if self.verbose:
            total = self.params.get("epochs")
            print(f"Epoch {epoch + 1}/{total}", file=sys.stderr)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {_fmt(v)}"
                               for k, v in (logs or {}).items())
            steps = self.params.get("steps")
            print(f"step {step + 1}/{steps} - {items}", file=sys.stderr)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = " - ".join(f"{k}: {_fmt(v)}"
                               for k, v in (logs or {}).items())
            dt = time.time() - self._t0
            print(f"Epoch {epoch + 1} done ({dt:.1f}s) - {items}",
                  file=sys.stderr)

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = " - ".join(f"{k}: {_fmt(v)}"
                               for k, v in (logs or {}).items())
            print(f"Eval - {items}", file=sys.stderr)


def _fmt(v):
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(f"{float(x):.4f}" for x in np.ravel(v)) + "]"
    try:
        return f"{float(v):.4f}"
    except (TypeError, ValueError):
        return str(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        if baseline is not None:
            self.best = baseline
        else:
            self.best = np.inf if mode == "min" else -np.inf
        self.wait = 0
        self.stop_training = False
        self._epoch = 0

    def _better(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
            save_dir = self.params.get("save_dir")
            if self.save_best_model and save_dir:
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                self.stopped_epoch = self._epoch
                self.model.stop_training = True
                if self.verbose:
                    import sys
                    print(f"Epoch {self._epoch}: early stopping "
                          f"(best {self.monitor}={self.best:.5f})",
                          file=sys.stderr)


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step or by_epoch)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.min_lr = min_lr
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.best = np.inf if mode == "min" else -np.inf
        self.wait = 0

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0])
        better = cur < self.best if self.mode == "min" else cur > self.best
        if better:
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                new_lr = max(float(opt.get_lr()) * self.factor, self.min_lr)
                opt.set_lr(new_lr)
            self.wait = 0


class TelemetryCallback(Callback):
    """Model.fit integration of the training flight recorder
    (`paddle_tpu.telemetry`): every train batch becomes one step record
    in a JSONL log — wall time, compile/execute split, tokens/sec, MFU,
    memory, per-collective time — and host spans export as a Chrome
    trace. The VisualDL scalars file tells you WHAT the loss did; this
    tells you WHERE the step time went.

    cb = TelemetryCallback("run.jsonl", tokens_per_step=B*S,
                           flops_per_token=telemetry.model_flops_per_token(...))
    model.fit(..., callbacks=[cb]); cb.recorder.records / cb.export(path)

    health: True | dict | telemetry.HealthConfig | HealthMonitor wires
    the training health monitor at RECORD level: every batch's loss and
    wall time run through the anomaly rules (loss spikes, NaN, step-time
    regression) with the configured warn/record/raise action, and the
    hang watchdog (config.hang_deadline_s) is armed around each batch —
    a Model.fit loop gets black-box hang dumps with zero extra code.
    (Device-side grad taps need the step object; use TrainStep/
    ShardedTrainStep health= for those.)
    """

    def __init__(self, path=None, tokens_per_step=None, flops_per_step=None,
                 flops_per_token=None, peak_flops=None, recorder=None,
                 health=None):
        super().__init__()
        if recorder is None:
            from .. import telemetry
            recorder = telemetry.TelemetryRecorder(
                sink=path, tokens_per_step=tokens_per_step,
                flops_per_step=flops_per_step,
                flops_per_token=flops_per_token, peak_flops=peak_flops)
        self.recorder = recorder
        from ..telemetry import health as _health
        self.health = _health.as_monitor(health)
        self._activated = False
        self._batch_t0 = None

    def on_train_begin(self, logs=None):
        # context-activate the recorder for the whole fit: collective /
        # pipeline / h2d spans (telemetry.span) record into the ACTIVE
        # recorder only. TrainStep's auto_step stays inert because this
        # callback opens the step window first (on_train_batch_begin
        # fires before train_batch), so the loss still attaches here.
        if not self._activated:
            self.recorder.__enter__()
            self._activated = True

    def on_train_batch_begin(self, step, logs=None):
        if not self.recorder._open:
            self.recorder.start_step()
        if self.health is not None:
            self.health.step_open()
            self._batch_t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        if self.recorder._open:
            loss = (logs or {}).get("loss")
            if isinstance(loss, (list, tuple)) and loss:
                loss = loss[0]
            loss = np.ravel(loss)[0] if loss is not None else None
            fields = {}
            if self.health is not None:
                step_ms = None
                if self._batch_t0 is not None:
                    step_ms = (time.perf_counter() - self._batch_t0) * 1000.0
                lv = None if loss is None else float(loss)
                fields = self.health.step_close(
                    loss=lv, step_ms=step_ms) or {}
            self.recorder.end_step(loss=loss, **fields)

    def on_train_end(self, logs=None):
        if self.recorder._open:   # tail window from an aborted batch
            self.recorder.end_step()
        if self.health is not None:
            self.health.close()   # stop the watchdog thread
        if self._activated:
            self.recorder.__exit__(None, None, None)
            self._activated = False

    def export(self, path, extra_sources=(), align_on=None):
        """Write this run's host spans as a Chrome trace."""
        return self.recorder.export_chrome_tracing(
            path, extra_sources=extra_sources, align_on=align_on)


class VisualDL(Callback):
    """Scalar logger (reference logs to VisualDL; here a simple JSONL file,
    TensorBoard-compatible via jax.profiler for traces)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json
        os.makedirs(self.log_dir, exist_ok=True)
        self._step += 1
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            rec = {"step": self._step}
            for k, v in (logs or {}).items():
                try:
                    rec[k] = float(np.ravel(v)[0])
                except (TypeError, ValueError):
                    pass
            f.write(json.dumps(rec) + "\n")
