"""High-level Model API.

Parity: `python/paddle/hapi/model.py:876` (Model), `fit:1521`, evaluate,
predict, save/load, train_batch/eval_batch. TPU-native: a single fused
jitted TrainStep replaces the reference's dual dygraph/static adapters
(`hapi/model.py:247,657`) — one code path, one XLA program per step.
"""
import os

import numpy as np

from ..core.tensor import Tensor
from ..jit import TrainStep
from ..io.dataloader import DataLoader, Dataset
from ..io import serialization
from ..metric import Metric
from .callbacks import CallbackList, ProgBarLogger


def _metric_items(m):
    """paddle Metric.name()/accumulate() may return lists — zip them."""
    names = m.name()
    names = names if isinstance(names, (list, tuple)) else [names]
    vals = m.accumulate()
    vals = vals if isinstance(vals, (list, tuple)) else [vals]
    return list(zip(names, vals))


def _as_tuple(x):
    if x is None:
        return ()
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,)


class Model:
    """`Model(network)` then `prepare(optimizer, loss, metrics)` then
    `fit/evaluate/predict`. inputs/labels InputSpecs are accepted for API
    parity and used for `save(training=False)` export."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs_spec = _as_tuple(inputs)
        self._labels_spec = _as_tuple(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, lint=False):
        # lint: graph-doctor pre-flight (paddle_tpu.analysis) — False |
        # True (warn on findings) | "strict" (raise on errors); runs the
        # jaxpr/sharding passes when the fused train step first traces
        self._optimizer = optimizer
        self._loss = loss
        self._lint = lint
        metrics = metrics or []
        self._metrics = list(metrics) if isinstance(
            metrics, (list, tuple)) else [metrics]
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m!r} is not a paddle Metric")
        self._train_step = None

    def _split_batch(self, batch):
        batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
        # split labels off whenever anything will consume them — a loss OR
        # metrics (metrics-only evaluation is supported, hapi/model.py ref)
        if (self._loss is None and not self._metrics) or len(batch) == 1:
            return batch, []
        n_lab = max(1, len(self._labels_spec)) if self._labels_spec else 1
        return batch[:-n_lab], batch[-n_lab:]

    def _loss_value(self, outputs, labels):
        outs = _as_tuple(outputs)
        loss = self._loss(*outs, *labels)
        if isinstance(loss, (list, tuple)):
            loss = sum(loss[1:], loss[0])
        return loss

    # ------------------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True,
                    loss_scale=1.0):
        """One training step. update=True runs the fused jitted
        fwd+bwd+update program; update=False accumulates grads eagerly
        (loss scaled by `loss_scale`) for gradient accumulation."""
        inputs = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
                  for t in _as_tuple(inputs)]
        labels = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
                  for t in _as_tuple(labels)]
        if self._optimizer is None or self._loss is None:
            raise RuntimeError("call prepare(optimizer, loss) before "
                               "train_batch")
        n_in = len(inputs)

        has_pending = any(
            p.grad is not None for p in self.network.parameters()
            if not p.stop_gradient)
        if not update or loss_scale != 1.0 or has_pending:
            # eager accumulate path: grads sum into .grad across calls;
            # the optimizer steps only when update=True. Also taken when
            # grads are already pending so a fused step never discards an
            # accumulation in progress.
            outs = self.network(*inputs)
            loss = self._loss_value(outs, labels)
            # backprop the scaled loss (so accumulated grads average), but
            # report the true micro-batch loss to callbacks/logs
            (loss * loss_scale if loss_scale != 1.0 else loss).backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
            return [loss.numpy()]

        if self._train_step is None:
            self._n_in = n_in

            def loss_fn(*batch):
                outs = self.network(*batch[:self._n_in])
                return self._loss_value(outs, list(batch[self._n_in:]))

            # reference fleet path (`fleet_base.py:881`): a fleet-wrapped
            # optimizer or an installed multi-device mesh means the step
            # must run GSPMD-sharded — params placed per their tags,
            # batch sharded over dp, ZeRO/offload from the strategy
            from ..distributed import env as dist_env
            mesh = dist_env.current_mesh()
            fleet_wrapped = hasattr(self._optimizer,
                                    "user_defined_strategy")
            if fleet_wrapped or (mesh is not None
                                 and mesh.devices.size > 1):
                from ..distributed.sharded_train import (ShardedTrainStep,
                                                         shard_model)
                if mesh is None:
                    # fleet-wrapped but fleet.init not yet called: run
                    # it with the optimizer's strategy so hybrid_configs
                    # (mp/pp/sp/ep degrees) shape the mesh — a hand-built
                    # dp-only mesh would silently drop the requested
                    # model parallelism. fleet.init installs the global
                    # mesh by design (reference fleet semantics).
                    from ..distributed import fleet as _fleet
                    _fleet.init(
                        is_collective=True,
                        strategy=self._optimizer.user_defined_strategy)
                    mesh = dist_env.current_mesh()
                shard_model(self.network, mesh)
                self._train_step = ShardedTrainStep(
                    self.network, loss_fn, self._optimizer, mesh=mesh,
                    lint=getattr(self, "_lint", False))
            else:
                self._train_step = TrainStep(
                    self.network, loss_fn, self._optimizer,
                    lint=getattr(self, "_lint", False))
        loss = self._train_step(*inputs, *labels)
        return [loss.numpy()]

    def eval_batch(self, inputs, labels=None):
        from ..core import autograd
        inputs = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
                  for t in _as_tuple(inputs)]
        labels = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
                  for t in _as_tuple(labels)]
        self.network.eval()
        try:
            with autograd.no_grad():
                outs = self.network(*inputs)
                metrics = {}
                if self._loss is not None and labels:
                    loss = self._loss_value(outs, labels)
                    metrics["loss"] = loss.numpy()
                for m in self._metrics:
                    res = m.compute(*_as_tuple(outs), *labels)
                    m.update(*[np.asarray(r.numpy() if isinstance(r, Tensor)
                                          else r) for r in _as_tuple(res)])
                    metrics.update(_metric_items(m))
        finally:
            self.network.train()
        return metrics

    def predict_batch(self, inputs):
        from ..core import autograd
        inputs = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
                  for t in _as_tuple(inputs)]
        self.network.eval()
        try:
            with autograd.no_grad():
                outs = self.network(*inputs)
        finally:
            self.network.train()
        return [o.numpy() for o in _as_tuple(outs)]

    # ------------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle, num_workers=0):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            # num_workers rides through to the async prefetch pipeline
            # (io.prefetch) — fit(num_workers=N) was previously accepted
            # and silently ignored. Default worker_mode="auto" means
            # THREADS sharing this one dataset object: a dataset with
            # per-instance mutable state (own RandomState, file handle,
            # parse buffer) must be wrapped in an explicit
            # DataLoader(worker_mode="process") and passed in directly
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers)
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        train_loader = self._loader(train_data, batch_size, shuffle,
                                    num_workers)
        eval_loader = self._loader(eval_data, batch_size, False, num_workers)
        cbks = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbks):
            cbks.insert(0, ProgBarLogger(log_freq, verbose=verbose))
        if save_dir:
            from .callbacks import ModelCheckpoint
            cbks.append(ModelCheckpoint(save_freq, save_dir))
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cblist = CallbackList(cbks, model=self,
                              params={"epochs": epochs, "steps": steps,
                                      "verbose": verbose,
                                      "save_dir": save_dir})
        self.stop_training = False
        cblist.on_train_begin()
        history = []
        it_count = 0
        for epoch in range(epochs):
            cblist.on_epoch_begin(epoch)
            self.network.train()
            logs = {}
            accum = max(1, accumulate_grad_batches)
            step = -1
            for step, batch in enumerate(train_loader):
                cblist.on_train_batch_begin(step)
                inputs, labels = self._split_batch(batch)
                if accum > 1:
                    loss = self.train_batch(
                        inputs, labels, update=(step + 1) % accum == 0,
                        loss_scale=1.0 / accum)
                else:
                    loss = self.train_batch(inputs, labels)
                logs = {"loss": loss}
                cblist.on_train_batch_end(step, logs)
                it_count += 1
                if num_iters is not None and it_count >= num_iters:
                    self.stop_training = True
                    break
            if accum > 1 and (step + 1) % accum != 0:
                # flush tail micro-batches so no gradient is dropped or
                # leaks into the next epoch
                self._optimizer.step()
                self._optimizer.clear_grad()
            if eval_loader is not None and (epoch % eval_freq == 0 or
                                            epoch == epochs - 1):
                eval_logs = self._run_eval(eval_loader, cblist)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cblist.on_epoch_end(epoch, logs)
            history.append(logs)
            if self.stop_training:
                break
        cblist.on_train_end(logs if history else {})
        return history

    def _run_eval(self, eval_loader, cblist):
        cblist.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(eval_loader):
            cblist.on_eval_batch_begin(step)
            inputs, labels = self._split_batch(batch)
            metrics = self.eval_batch(inputs, labels)
            if "loss" in metrics:
                losses.append(np.ravel(metrics["loss"])[0])
            cblist.on_eval_batch_end(step, metrics)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs.update(_metric_items(m))
        cblist.on_eval_end(logs)
        # drop the eval loader's one-shot input-wait stats: the next
        # recorded TRAIN step must not report this pass's fetch wait as
        # its own (io.prefetch keeps a single process-global slot)
        from ..io.prefetch import consume_step_input_stats
        consume_step_input_stats()
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._loader(eval_data, batch_size, False, num_workers)
        cblist = CallbackList(callbacks or [], model=self, params={})
        return self._run_eval(loader, cblist)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False, num_workers)
        outputs = None
        for batch in loader:
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            outs = self.predict_batch(batch)
            if outputs is None:
                outputs = [[] for _ in outs]
            for acc, o in zip(outputs, outs):
                acc.append(o)
        if outputs is None:
            return []
        if stack_outputs:
            return [np.concatenate(o, axis=0) for o in outputs]
        return outputs

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        """training=True: params (+ opt state) for resume; training=False:
        inference export (reference `hapi/model.py` save semantics)."""
        if not training:
            from ..inference.export import save_inference_model
            spec = list(self._inputs_spec) or None
            save_inference_model(path, self.network, input_spec=spec)
            return
        dirname = os.path.dirname(os.path.abspath(path))
        os.makedirs(dirname, exist_ok=True)
        serialization.save(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            serialization.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        sd = serialization.load(path + ".pdparams")
        self.network.set_state_dict(sd)
        opt_path = path + ".pdopt"
        if (self._optimizer is not None and not reset_optimizer and
                os.path.exists(opt_path)):
            self._optimizer.set_state_dict(serialization.load(opt_path))
        self._train_step = None

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary
        if input_size is None and self._inputs_spec:
            input_size = [tuple(s.shape) for s in self._inputs_spec]
        return summary(self.network, input_size, dtypes=dtype)
