"""FLOPs estimation (reference `python/paddle/hapi/dynamic_flops.py`):
per-layer multiply-add counts via hooked dry-run forward."""
import numpy as np

from ..core.tensor import Tensor
from ..core import autograd


def _linear_flops(layer, inp, out):
    return int(np.prod(inp.shape)) * layer.weight.shape[-1]


def _conv_flops(layer, inp, out):
    kh_kw_cin = int(np.prod(layer.weight.shape[1:]))
    return int(np.prod(out.shape)) * kh_kw_cin


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total forward multiply-accumulate count for one input of
    `input_size`."""
    from ..nn.layer.layers import Layer
    from ..nn import Linear, Conv2D

    custom_ops = custom_ops or {}
    total = [0]
    hooks = []

    def make_hook(layer):
        def hook(lyr, inp, out):
            inp0 = inp[0] if isinstance(inp, (list, tuple)) else inp
            out0 = out[0] if isinstance(out, (list, tuple)) else out
            fn = custom_ops.get(type(lyr))
            if fn is not None:
                total[0] += int(fn(lyr, inp0, out0))
            elif isinstance(lyr, Linear):
                total[0] += _linear_flops(lyr, inp0, out0)
            elif isinstance(lyr, Conv2D):
                total[0] += _conv_flops(lyr, inp0, out0)
        return hook

    for _, layer in net.named_sublayers():
        if not list(layer.children()):
            hooks.append(layer.register_forward_post_hook(make_hook(layer)))

    shape = tuple(1 if d in (None, -1) else int(d) for d in input_size)
    x = Tensor(np.random.rand(*shape).astype(np.float32))
    was_training = net.training
    net.eval()
    try:
        with autograd.no_grad():
            net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"Total FLOPs (MACs): {total[0]:,}")
    return total[0]
