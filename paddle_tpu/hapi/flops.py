"""FLOPs estimation (reference `python/paddle/hapi/dynamic_flops.py`):
per-layer multiply-add counts via hooked dry-run forward."""
import numpy as np

from ..core.tensor import Tensor
from ..core import autograd


def _linear_flops(layer, inp, out):
    return int(np.prod(inp.shape)) * layer.weight.shape[-1]


def _conv_flops(layer, inp, out):
    kh_kw_cin = int(np.prod(layer.weight.shape[1:]))
    return int(np.prod(out.shape)) * kh_kw_cin


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total forward multiply-accumulate count for one input of
    `input_size`."""
    from ..nn.layer.layers import Layer
    from ..nn import Linear, Conv2D

    custom_ops = custom_ops or {}
    total = [0]
    hooks = []

    def make_hook(layer):
        def hook(lyr, inp, out):
            inp0 = inp[0] if isinstance(inp, (list, tuple)) else inp
            out0 = out[0] if isinstance(out, (list, tuple)) else out
            fn = custom_ops.get(type(lyr))
            if fn is not None:
                total[0] += int(fn(lyr, inp0, out0))
            elif isinstance(lyr, Linear):
                total[0] += _linear_flops(lyr, inp0, out0)
            elif isinstance(lyr, Conv2D):
                total[0] += _conv_flops(lyr, inp0, out0)
        return hook

    for _, layer in net.named_sublayers():
        if not list(layer.children()):
            hooks.append(layer.register_forward_post_hook(make_hook(layer)))

    shape = tuple(1 if d in (None, -1) else int(d) for d in input_size)
    x = Tensor(np.random.rand(*shape).astype(np.float32))
    was_training = net.training
    net.eval()
    try:
        with autograd.no_grad():
            net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"Total FLOPs (MACs): {total[0]:,}")
    return total[0]


def flops_compiled(net_or_fn, input_spec, backprop=False, net=None):
    """EXACT cost-model feedback from the compiled program: lower the
    forward (or the full backward when backprop=True) through XLA and
    read the compiler's own cost analysis — flops and bytes accessed.
    This is the feedback loop the hook-based estimate above cannot give
    (fusion, rematerialization, and backward costs are all invisible to
    layer hooks). Returns {"flops": float, "bytes_accessed": float}.

    backprop=True differentiates w.r.t. the inputs AND the model
    parameters (pass `net` when net_or_fn is a plain function closing
    over a Layer; when net_or_fn IS a Layer its own parameters are
    used) — otherwise the dL/dW contractions, about half of real
    backward cost, would be invisible closure constants.

    input_spec: list of example arrays / Tensors / (shape, dtype).
    """
    import jax
    import jax.numpy as jnp
    from ..nn.layer.layers import Layer
    from ..jit import bind_tensors

    examples = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            examples.append(spec._value)
        elif isinstance(spec, tuple) and len(spec) == 2 and \
                isinstance(spec[0], (list, tuple)):
            examples.append(jnp.zeros(spec[0], spec[1]))
        else:
            examples.append(jnp.asarray(np.asarray(spec)))

    layer = net if net is not None else (
        net_or_fn if isinstance(net_or_fn, Layer) else None)
    params = list(layer.parameters()) if layer is not None else []
    param_vals = [p._value for p in params]

    def fwd(pvals, *vals):
        with autograd.no_grad(), bind_tensors(params, pvals):
            out = net_or_fn(*[Tensor(v) for v in vals])
        outs = out if isinstance(out, (tuple, list)) else [out]
        return sum(jnp.sum(o._value.astype(jnp.float32)) for o in outs)

    if backprop:
        fn = jax.grad(fwd, argnums=tuple(range(1 + len(examples))))
    else:
        fn = fwd
    comp = jax.jit(fn).lower(param_vals, *examples).compile()
    # cost_analysis() raises on some backends (e.g. the axon plugin);
    # degrade to zeros like every other caller rather than failing
    from ..cost_model import _safe_cost_analysis
    ca = _safe_cost_analysis(comp)
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
