"""Model summary (reference `python/paddle/hapi/summary.py`): per-layer
output shapes and parameter counts via a hooked dry-run forward."""
import numpy as np

import jax

from ..core.tensor import Tensor
from ..core import autograd


def summary(net, input_size, dtypes=None, input=None):
    from ..nn.layer.layers import Layer

    if input is None:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = input_size if isinstance(input_size, list) else [input_size]
        sizes = [s if isinstance(s, (list, tuple)) else (s,) for s in sizes]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else \
            [dtypes or "float32"] * len(sizes)
        inputs = []
        for s, dt in zip(sizes, dts):
            shape = tuple(1 if d in (None, -1) else int(d) for d in s)
            if "int" in str(dt):
                inputs.append(Tensor(np.zeros(shape, dtype=str(dt))))
            else:
                inputs.append(Tensor(np.random.rand(*shape).astype(str(dt))))
    else:
        inputs = [input] if isinstance(input, Tensor) else list(input)

    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(lyr, inp, out):
            outs = out if isinstance(out, (list, tuple)) else [out]
            shape = [list(o.shape) for o in outs
                     if isinstance(o, Tensor)]
            n_params = sum(int(np.prod(p.shape))
                           for p in lyr.parameters(include_sublayers=False))
            rows.append((f"{type(lyr).__name__}-{len(rows) + 1}",
                         shape[0] if len(shape) == 1 else shape, n_params))
        return hook

    for name, layer in net.named_sublayers():
        if not list(layer.children()):
            hooks.append(layer.register_forward_post_hook(
                make_hook(name, layer)))

    was_training = net.training
    net.eval()
    try:
        with autograd.no_grad():
            net(*inputs)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    w = 30
    lines = ["-" * (w * 3),
             f"{'Layer (type)':<{w}}{'Output Shape':<{w}}{'Param #':<{w}}",
             "=" * (w * 3)]
    for name, shape, n in rows:
        lines.append(f"{name:<{w}}{str(shape):<{w}}{n:<{w}}")
    lines += ["=" * (w * 3),
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}",
              "-" * (w * 3)]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
