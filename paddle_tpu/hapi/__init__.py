"""paddle_tpu.hapi — the high-level training API.

Parity: `python/paddle/hapi/` (`Model hapi/model.py:876`, `fit:1521`,
callbacks `hapi/callbacks.py`, `summary hapi/summary.py`). TPU-native: the
Model wraps the fused jitted TrainStep, so `fit` runs one XLA program per
step instead of the reference's per-mode dygraph/static adapters
(`model.py:247,657`).
"""
from .model import Model  # noqa: F401
from .callbacks import (Callback, ProgBarLogger, ModelCheckpoint,  # noqa: F401
                        EarlyStopping, LRScheduler, ReduceLROnPlateau,
                        VisualDL)
from .summary import summary  # noqa: F401
