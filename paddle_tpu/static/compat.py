"""paddle.static parity surface: program/state serialization, places,
backward/metric helpers.

Reference: `python/paddle/static/__init__.py` exports backed by
`fluid/io.py` (save/load/serialize), `fluid/framework.py` (Variable,
scopes), `fluid/backward.py` (append_backward/gradients) and
`fluid/layers/metric_op.py` (accuracy/auc). The executable serialized
form of a program in this framework is the StableHLO artifact
(`inference.save_inference_model`); the serialize_* functions here cover
the PARAMETER/state side plus a structural program record, which is what
reference users round-trip through these APIs.
"""
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..core import autograd

__all__ = [
    "Variable", "accuracy", "auc", "append_backward", "gradients",
    "create_parameter", "create_global_var", "cpu_places", "cuda_places",
    "xpu_places", "global_scope", "scope_guard", "save", "load",
    "save_to_file", "load_from_file", "serialize_program",
    "deserialize_program", "serialize_persistables",
    "deserialize_persistables", "load_program_state",
    "set_program_state", "normalize_program", "ExponentialMovingAverage",
    "ParallelExecutor",
]

Variable = Tensor          # one tensor type in both "worlds" (L2 dissolves)


# ---------------------------------------------------------------- places

def cpu_places(device_count=None):
    from ..framework import CPUPlace
    n = device_count or int(os.environ.get("CPU_NUM", "1"))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """The accelerator-place list. On this framework the accelerator is
    whatever PJRT exposes (TPU); returns one place per visible chip."""
    from ..framework import TPUPlace
    if device_ids is None:
        device_ids = range(len(jax.devices()))
    return [TPUPlace(i) for i in device_ids]


xpu_places = cuda_places


# ---------------------------------------------------------------- scopes

class _Scope(dict):
    def var(self, name):
        return self.setdefault(name, Tensor(jnp.zeros((), jnp.float32)))

    def find_var(self, name):
        return self.get(name)


_GLOBAL_SCOPE = _Scope()
_SCOPE_STACK = [_GLOBAL_SCOPE]


def global_scope():
    return _SCOPE_STACK[-1]


class scope_guard:
    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        _SCOPE_STACK.append(self._scope)
        return self._scope

    def __exit__(self, *exc):
        _SCOPE_STACK.pop()
        return False


# ------------------------------------------------------------- backward

def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Reference `backward.py:1390`: emit gradients for `loss` and
    return [(param, grad)] pairs. Here the tape IS the program record —
    running backward materializes `.grad` on every trainable tensor."""
    autograd.backward(loss)
    if parameter_list is None:
        from . import default_main_program
        params = default_main_program().all_parameters()
    else:
        params = list(parameter_list)
    return [(p, p.grad) for p in params
            if p is not None and p.grad is not None]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return autograd.grad(targets, inputs,
                         grad_outputs=target_gradients)


# --------------------------------------------------------------- metrics

def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy (reference `metric_op.py accuracy`)."""
    def fn(logits, y):
        topk = jnp.argsort(logits, axis=-1)[..., -k:]
        y = y.reshape(y.shape[0], 1)
        hit = jnp.any(topk == y, axis=-1)
        return jnp.mean(hit.astype(jnp.float32)).reshape(1)
    return apply(fn, input, label)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, name=None):
    """Batch AUC via threshold buckets (reference `metric_op.py auc`).
    Returns (auc_value, batch_auc_value) like the reference tuple's
    leading entries."""
    def fn(probs, y):
        pos_prob = probs[:, 1] if probs.ndim == 2 and probs.shape[1] > 1 \
            else probs.reshape(-1)
        y = y.reshape(-1)
        edges = jnp.linspace(0.0, 1.0, num_thresholds + 1)
        idx = jnp.clip(jnp.searchsorted(edges, pos_prob) - 1, 0,
                       num_thresholds - 1)
        pos = jnp.zeros(num_thresholds).at[idx].add(y == 1)
        neg = jnp.zeros(num_thresholds).at[idx].add(y == 0)
        # integrate TPR over FPR (trapezoid over buckets, high->low thresh)
        tp = jnp.cumsum(pos[::-1])
        fp = jnp.cumsum(neg[::-1])
        tp_tot = jnp.maximum(tp[-1], 1)
        fp_tot = jnp.maximum(fp[-1], 1)
        tpr = tp / tp_tot
        fpr = fp / fp_tot
        a = jnp.sum((fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) / 2)
        a = a + fpr[0] * tpr[0] / 2
        return a.reshape(1)
    val = apply(fn, input, label)
    return val, val


# --------------------------------------------------- parameters / state

def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    import paddle_tpu
    return paddle_tpu.create_parameter(
        shape, dtype=dtype, name=name, attr=attr, is_bias=is_bias,
        default_initializer=default_initializer)


def create_global_var(shape, value, dtype="float32", persistable=False,
                      force_cpu=False, name=None):
    from ..core.dtype import convert_dtype
    t = Tensor(jnp.full(tuple(shape), value, convert_dtype(dtype)),
               stop_gradient=True)
    t.name = name or "global_var"
    global_scope()[t.name] = t
    return t


def _program_params(program):
    if program is None or not hasattr(program, "all_parameters"):
        from . import default_main_program
        program = default_main_program()
    return {getattr(p, "name", None) or f"param_{i}": p
            for i, p in enumerate(program.all_parameters())}


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None,
                           **kw):
    prog = program if program is not None else feed_vars  # 1-arg form
    params = _program_params(prog if not isinstance(prog, (list, tuple))
                             else None)
    blob = {n: np.asarray(p.numpy()) for n, p in params.items()}
    return pickle.dumps(blob, protocol=4)


def deserialize_persistables(program, data, executor=None, scope=None):
    # third param named `executor` like the reference (`static/io.py`);
    # it is unused here (no scope machinery to thread through), `scope`
    # stays as a trailing alias
    blob = pickle.loads(data)
    params = _program_params(program)
    for n, arr in blob.items():
        if n in params:
            params[n]._value = jnp.asarray(arr)
    return blob


def serialize_program(feed_vars=None, fetch_vars=None, program=None, **kw):
    """Structural program record. The EXECUTABLE serialized form is the
    StableHLO artifact (save_inference_model); this captures the
    recorder's var/op listing, which is what reference code inspects
    after deserialize_program."""
    prog = program if program is not None else feed_vars
    if prog is None or isinstance(prog, (list, tuple)):
        from . import default_main_program
        prog = default_main_program()
    record = {
        "vars": [getattr(v, "name", str(i))
                 for i, v in enumerate(prog.list_vars())],
        "ops": [op.fn.__name__ if hasattr(op, "fn") else str(op)
                for op in getattr(prog, "ops", [])],
    }
    return pickle.dumps(record, protocol=4)


class _DeserializedProgram:
    def __init__(self, record):
        self._record = record

    def list_vars(self):
        return list(self._record["vars"])

    @property
    def ops(self):
        return list(self._record["ops"])


def deserialize_program(data):
    return _DeserializedProgram(pickle.loads(data))


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def save(program, model_path, protocol=4, **configs):
    """Reference `static.save`: <path>.pdparams (+.pdmodel)."""
    save_to_file(model_path + ".pdparams",
                 serialize_persistables(program=program))
    save_to_file(model_path + ".pdmodel", serialize_program(program=program))


def load(program, model_path, executor=None, var_list=None):
    data = load_from_file(model_path + ".pdparams")
    deserialize_persistables(program, data)


def load_program_state(model_path, var_list=None):
    return {n: np.asarray(a) for n, a in
            pickle.loads(load_from_file(model_path + ".pdparams")).items()}


def set_program_state(program, state_dict):
    params = _program_params(program)
    for n, arr in state_dict.items():
        if n in params:
            params[n]._value = jnp.asarray(arr)


def normalize_program(program, feed_vars, fetch_vars, **kw):
    """Reference prunes the program to the inference subgraph; trace-
    compile re-derives that from the traced function, so the program
    passes through (clone-for-test semantics)."""
    return program.clone(for_test=True) if hasattr(program, "clone") \
        else program


# --------------------------------------------------------------- shims

from ..optimizer.extras import ExponentialMovingAverage  # noqa: E402,F401


class ParallelExecutor:
    """Compat face over Executor (reference `parallel_executor.cc`): the
    multi-device SSA executor dissolves into GSPMD — one compiled program
    spans the mesh — so this delegates to Executor and exposes the
    legacy attrs code touches."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from . import Executor
        self._exe = Executor()
        self._program = main_program

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(program=self._program,
                             feed=feed or feed_dict,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)

    @property
    def device_count(self):
        return len(jax.devices())
