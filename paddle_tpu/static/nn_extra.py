"""static.nn parity ops beyond the core zoo.

Reference surfaces: `python/paddle/static/nn/__init__.py` exports backed
by `fluid/layers/nn.py` (row_conv, bilinear_tensor_product, data_norm,
nce, spectral_norm, py_func), `fluid/layers/detection.py`
(multi_box_head), `fluid/layers/sequence_lod.py` (sequence_expand,
first/last_step, reshape, scatter) and `fluid/input.py`
(sparse_embedding). Sequence ops follow this framework's padded+lengths
LoD analog (`tensor/sequence.py`).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..tensor.sequence import ensure_tensor, _val, _lengths

__all__ = [
    "bilinear_tensor_product", "conv3d_transpose", "crf_decoding",
    "data_norm", "deform_conv2d", "multi_box_head", "nce", "py_func",
    "row_conv", "sequence_expand", "sequence_first_step",
    "sequence_last_step", "sequence_reshape", "sequence_scatter",
    "sparse_embedding", "spectral_norm",
]


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None, weight=None,
                            bias=None):
    """out[:, k] = x W_k y^T + b_k with W [size, dx, dy] (reference
    `fluid/layers/nn.py bilinear_tensor_product`). Pass `weight`/`bias`
    tensors directly, or they are created on first call."""
    import paddle_tpu
    dx = x.shape[-1]
    dy = y.shape[-1]
    if weight is None:
        weight = paddle_tpu.create_parameter([size, dx, dy], attr=param_attr)
    if bias is None and bias_attr is not False:
        bias = paddle_tpu.create_parameter([size], attr=bias_attr,
                                           is_bias=True)

    def fn(xv, wv, yv, *b):
        out = jnp.einsum("bi,kij,bj->bk", xv, wv, yv)
        if b:
            out = out + b[0]
        return out
    args = (x, weight, y) + ((bias,) if bias is not None else ())
    out = apply(fn, *args)
    if act == "tanh":
        from ..nn import functional as F
        out = F.tanh(out)
    return out


def conv3d_transpose(input, num_filters=None, output_size=None,
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None,
                     data_format="NCDHW", weight=None, bias=None, **kw):
    """NCDHW transposed 3D convolution (reference conv3d_transpose,
    `fluid/layers/nn.py:4088` — same param order). `weight`
    [in, out, kd, kh, kw] is this backend's explicit-tensor extension
    (trailing, defaulted). use_cudnn is the obviated CUDA kernel hint;
    dilation/groups != 1, output_size and act are not implemented here
    and raise."""
    if dilation != 1 or groups != 1 or output_size is not None or act:
        raise NotImplementedError(
            "conv3d_transpose: dilation/groups/output_size/act are not "
            "supported by this backend's functional form")
    if weight is None:
        raise ValueError("conv3d_transpose needs an explicit weight "
                         "tensor in functional form")
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)

    def fn(xv, wv, *b):
        # transposed conv == conv of the stride-dilated input with the
        # spatially-flipped kernel; out = (in-1)*s - 2*p + k (paddle
        # semantics — lax.conv_transpose's own padding rule differs)
        wv = jnp.flip(wv, axis=(2, 3, 4))           # [in, out, kd, kh, kw]
        wv = jnp.swapaxes(wv, 0, 1)                 # -> OIDHW
        k = wv.shape[2:]
        pads = [(k[i] - 1 - p[i], k[i] - 1 - p[i]) for i in range(3)]
        out = jax.lax.conv_general_dilated(
            xv, wv, window_strides=(1, 1, 1), padding=pads,
            lhs_dilation=s,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
        if b:
            out = out + b[0].reshape(1, -1, 1, 1, 1)
        return out
    args = (input, weight) + ((bias,) if bias is not None else ())
    return apply(fn, *args)


def crf_decoding(input, transition, length=None, label=None, name=None):
    """Viterbi best-path decode (reference `crf_decoding_op.cc`); routes
    to the text.viterbi implementation over padded+lengths batches."""
    from ..text import viterbi_decode
    scores, path = viterbi_decode(input, transition, lengths=length)
    return path


def data_norm(input, epsilon=1e-5, name=None, batch_size_default=1e4,
              batch_sum_default=0.0, batch_square_sum_default=1e4,
              summary_decay_rate=0.9999999, **kw):
    """Reference `data_norm` op: normalize each feature by accumulated
    batch statistics WITHOUT affine params (CTR models). Functional
    form: stats are computed from the batch (the accumulated-summary
    machinery belongs to the PS runtime)."""
    def fn(v):
        mean = jnp.mean(v, axis=0, keepdims=True)
        var = jnp.mean((v - mean) ** 2, axis=0, keepdims=True)
        return (v - mean) / jnp.sqrt(var + epsilon)
    return apply(fn, ensure_tensor(input))


def deform_conv2d(*args, **kw):
    from ..vision.ops import deform_conv2d as _dc
    return _dc(*args, **kw)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, offset=0.5, flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False, **kw):
    """SSD detection head (reference `fluid/layers/detection.py
    multi_box_head`): per-feature-map 1x1/3x3 convs predicting box
    deltas + class scores, plus the prior boxes. Functional TPU form:
    conv weights are created per call site via nn.Conv2D composition is
    the Layer path; here we emit predictions with fresh parameters,
    matching the reference's create-on-build semantics."""
    from .. import nn
    from ..vision.detection import prior_box as _prior_box
    if min_sizes is None:
        # reference ratio schedule
        n = len(inputs)
        min_sizes, max_sizes = [], []
        step = int(np.floor((max_ratio - min_ratio) / max(n - 2, 1)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n - 1]
    locs, confs, boxes, vars_ = [], [], [], []
    for i, x in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) \
            else [aspect_ratios[i]]
        ms = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        mx = [max_sizes[i]] if max_sizes and max_sizes[i] else None
        box, var = _prior_box(x, image, ms, mx, ar, flip=flip, clip=clip,
                              offset=offset,
                              steps=[steps[i]] * 2 if steps else [0., 0.])
        num_priors = int(np.prod(box.shape[:-1])) // (
            x.shape[2] * x.shape[3])
        loc_conv = nn.Conv2D(x.shape[1], num_priors * 4, kernel_size,
                             padding=pad, stride=stride)
        conf_conv = nn.Conv2D(x.shape[1], num_priors * num_classes,
                              kernel_size, padding=pad, stride=stride)
        loc = loc_conv(x)
        conf = conf_conv(x)

        def _nhwc_flat(t, last):
            v = t.transpose([0, 2, 3, 1])
            return v.reshape([v.shape[0], -1, last])
        locs.append(_nhwc_flat(loc, 4))
        confs.append(_nhwc_flat(conf, num_classes))
        boxes.append(box.reshape([-1, 4]))
        vars_.append(var.reshape([-1, 4]))
    import paddle_tpu
    mbox_locs = paddle_tpu.concat(locs, axis=1)
    mbox_confs = paddle_tpu.concat(confs, axis=1)
    all_boxes = paddle_tpu.concat(boxes, axis=0)
    all_vars = paddle_tpu.concat(vars_, axis=0)
    return mbox_locs, mbox_confs, all_boxes, all_vars


_NCE_RNG = np.random.RandomState(12345)


def nce(input, label, num_total_classes, sample_weight=None,
        num_neg_samples=10, name=None, weight=None, bias=None, seed=None,
        **kw):
    """Noise-contrastive estimation loss (reference `nce_op.cc`):
    logistic loss on the true class + `num_neg_samples` uniform negative
    classes, RESAMPLED per forward (a fixed `seed` pins them — tests
    only). weight [num_total_classes, dim] required."""
    if weight is None:
        raise ValueError("nce needs an explicit weight [classes, dim]")
    rs = np.random.RandomState(seed) if seed is not None else _NCE_RNG
    neg = rs.randint(0, num_total_classes,
                     (int(num_neg_samples),)).astype(np.int64)

    def fn(xv, wv, yv, *b):
        yv = yv.reshape(-1)
        w_pos = wv[yv]                               # [B, D]
        pos_logit = jnp.sum(xv * w_pos, -1)
        w_neg = wv[jnp.asarray(neg)]                 # [K, D]
        neg_logit = xv @ w_neg.T                     # [B, K]
        if b:
            pos_logit = pos_logit + b[0][yv]
            neg_logit = neg_logit + b[0][jnp.asarray(neg)][None, :]
        pos_loss = jax.nn.softplus(-pos_logit)
        neg_loss = jnp.sum(jax.nn.softplus(neg_logit), -1)
        return (pos_loss + neg_loss).reshape(-1, 1)
    args = (input, weight, label) + ((bias,) if bias is not None else ())
    return apply(fn, *args)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-Python op (reference `py_func_op.cc`). Eagerly this is a
    direct call; under trace it lowers to `jax.pure_callback` with the
    declared `out` shape/dtype. backward_func is honored eagerly via
    a custom vjp when provided."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), o._value.dtype)
              for o in outs]

    def host(*vals):
        r = func(*[np.asarray(v) for v in vals])
        rs = r if isinstance(r, (list, tuple)) else [r]
        return tuple(np.asarray(v) for v in rs)

    def fn(*vals):
        res = jax.pure_callback(host, tuple(shapes), *vals)
        return res if len(res) > 1 else res[0]
    result = apply(fn, *xs)
    return result


def row_conv(input, future_context_size, param_attr=None, act=None,
             weight=None):
    """Lookahead row convolution (reference `row_conv_op.cc`,
    DeepSpeech2): out[t] = sum_{i=0..k} w[i] * x[t+i], zero past the
    end. input [B, T, D], weight [k+1, D]."""
    import paddle_tpu
    k = int(future_context_size)
    if weight is None:
        weight = paddle_tpu.create_parameter(
            [k + 1, int(input.shape[-1])], attr=param_attr)

    def fn(xv, wv):
        T = xv.shape[1]
        out = jnp.zeros_like(xv)
        for i in range(k + 1):
            shifted = jnp.pad(xv[:, i:], ((0, 0), (0, i), (0, 0)))
            out = out + shifted * wv[i]
        return out
    out = apply(fn, input, weight)
    if act == "tanh":
        from ..nn import functional as F
        out = F.tanh(out)
    return out


# ------------------------------------------------ sequence-family extras

def sequence_expand(x, y_lengths, ref_level=0, name=None):
    """Repeat each row of x per the reference sequence's lengths
    (reference `sequence_expand_op.cc`): row i appears y_lengths[i]
    times, rows packed then padded to [B, max_len, ...]."""
    from ..tensor.sequence import sequence_expand_as
    return sequence_expand_as(x, y_lengths)


def sequence_first_step(input, lengths=None, name=None):
    """First timestep of each row ([B, T, D] + lengths -> [B, D])."""
    def fn(v):
        return v[:, 0]
    return apply(fn, ensure_tensor(input))


def sequence_last_step(input, lengths=None, name=None):
    """Last VALID timestep of each row (reference
    `sequence_pool_op.cc` LAST pooling)."""
    xv = _val(ensure_tensor(input))
    if lengths is None:
        def fn(v):
            return v[:, -1]
        return apply(fn, ensure_tensor(input))
    lv = _lengths(lengths)
    idx = jnp.maximum(lv - 1, 0)

    def fn(v):
        return jnp.take_along_axis(
            v, idx.reshape(-1, 1, *([1] * (v.ndim - 2))).astype(jnp.int32),
            axis=1)[:, 0]
    return apply(fn, ensure_tensor(input))


def sequence_reshape(input, new_dim, lengths=None, name=None):
    """Refold timesteps so the feature dim becomes new_dim (reference
    `sequence_reshape_op.cc`): [B, T, D] -> [B, T*D/new_dim, new_dim]."""
    def fn(v):
        B = v.shape[0]
        return v.reshape(B, -1, new_dim)
    out = apply(fn, ensure_tensor(input))
    if lengths is None:
        return out
    lv = _lengths(lengths)
    d = int(np.prod(ensure_tensor(input).shape[2:]))
    return out, Tensor(lv * d // new_dim)


def sequence_scatter(input, index, updates, lengths=None, name=None):
    """Scatter per-row updates into the padded sequence (reference
    `sequence_scatter_op.cc`): input [B, T, ...], index [B, K] time
    positions, updates [B, K, ...] ADDED at those positions."""
    def fn(v, idx, upd):
        B = v.shape[0]
        bidx = jnp.arange(B)[:, None]
        return v.at[bidx, idx].add(upd)
    return apply(fn, ensure_tensor(input), ensure_tensor(index),
                 ensure_tensor(updates))


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, param_attr=None, dtype="float32",
                     **kw):
    """Reference `fluid/input.py sparse_embedding` — the PS-backed
    embedding. In-process form: a dense Embedding lookup; the
    distributed PS-backed path lives in `distributed.ps.SparseTable`
    (pull/push from the table happens in the CTR loop, see
    tests/test_dataset_ctr.py)."""
    from .. import nn
    emb = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                       weight_attr=param_attr)
    return emb(ensure_tensor(input))


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectral normalization (reference `spectral_norm_op.cc`): divide
    by the largest singular value estimated with power iteration."""
    def fn(w):
        mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((mat.shape[0],), w.dtype) / np.sqrt(mat.shape[0])
        v = None
        for _ in range(max(1, power_iters)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ mat @ v
        return w / (sigma + eps)
    return apply(fn, ensure_tensor(weight))
