"""paddle_tpu.static — static-graph API parity.

Reference: `python/paddle/static/` over fluid's Program/Executor world
(`framework.py:4307` Program, `executor.py:606,1055` Executor.run,
`backward.py:1390` append_backward). TPU-native design: there is no second
execution engine — building "static" ops just runs the same eager ops while
a Program recorder captures each `apply` as a replayable forward node (the
ProgramDesc analog). `Executor.run` re-binds the feed into the placeholder
tensors, replays the nodes in place (re-taping them so autograd works), then
runs any `optimizer.minimize` hooks recorded at build time. `CompiledProgram`
jit-compiles the same replay into one fused XLA program.
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp

from ..core import autograd
from ..core import tensor as core_tensor
from ..core.tensor import Tensor
from ..jit import InputSpec  # noqa: F401  (paddle.static.InputSpec)
from ..inference.export import (save_inference_model,  # noqa: F401
                                load_inference_model)
from . import nn  # noqa: F401
from .control_flow import (while_loop, cond, case,  # noqa: F401
                           switch_case, Assert)


class _ProgramOp:
    __slots__ = ("fn", "inputs", "outputs", "multi")

    def __init__(self, fn, inputs, outputs, multi):
        self.fn = fn
        self.inputs = inputs
        self.outputs = outputs
        self.multi = multi


class Program:
    """Recorded forward ops + feed placeholders + train hooks."""

    def __init__(self):
        self.ops = []
        self.placeholders = {}
        self.train_hooks = []  # [(optimizer, loss_tensor)]
        self.random_seed = None

    # recorder protocol (core.tensor capture)
    def record_op(self, fn, inputs, outputs, multi):
        self.ops.append(_ProgramOp(fn, inputs, outputs, multi))

    def add_train_hook(self, optimizer, loss):
        self.train_hooks.append((optimizer, loss))

    def add_placeholder(self, name, t):
        self.placeholders[name] = t

    def global_block(self):
        return self

    def clone(self, for_test=False):
        p = Program()
        p.ops = list(self.ops)
        p.placeholders = dict(self.placeholders)
        if not for_test:
            p.train_hooks = list(self.train_hooks)
        return p

    def list_vars(self):
        seen, out = set(), []
        for op in self.ops:
            for t in list(op.inputs) + list(op.outputs):
                if id(t) not in seen:
                    seen.add(id(t))
                    out.append(t)
        return out

    def all_parameters(self):
        """Trainable leaf tensors (reference Program.all_parameters) — the
        default parameter list for optimizers built in pure static.nn
        flows."""
        return [t for t in self.list_vars()
                if not t.stop_gradient and not t._has_producer]

    def __repr__(self):
        return (f"Program(ops={len(self.ops)}, "
                f"placeholders={list(self.placeholders)}, "
                f"train_hooks={len(self.train_hooks)})")


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    prev = (_default_main, _default_startup)
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    core_tensor.push_capture(main_program)
    try:
        yield
    finally:
        core_tensor.pop_capture()
        _default_main, _default_startup = prev


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (reference `static/input.py` paddle.static.data).
    Holds zeros until Executor.run binds the feed."""
    from ..core.dtype import convert_dtype
    concrete = tuple(1 if d in (None, -1) else int(d) for d in shape)
    t = Tensor(jnp.zeros(concrete, convert_dtype(dtype)), stop_gradient=True)
    t.name = name
    t._is_placeholder = True
    prog = core_tensor.active_capture() or _default_main
    prog.add_placeholder(name, t)
    return t


class Executor:
    """Replays a Program (reference `executor.py:1055` Executor.run — the
    op loop `framework/executor.cc:485` becomes an in-place node replay)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        if program is None:
            program = _default_main
        if not isinstance(program, Program):
            raise TypeError(f"not a static Program: {program!r}")
        feed = feed or {}
        for name, val in feed.items():
            t = program.placeholders.get(name)
            if t is None:
                raise KeyError(
                    f"feed '{name}' is not a placeholder of this program "
                    f"(have {list(program.placeholders)})")
            t._value = jnp.asarray(val).astype(t._value.dtype)

        # replay only re-tapes when there are train hooks to backprop;
        # pure-inference replays skip the vjp cost entirely
        taping = bool(program.train_hooks) and autograd.grad_enabled()
        tape_mark = autograd.tape_size()
        for op in program.ops:
            vals = tuple(t._value for t in op.inputs)
            requires = taping and any(
                not t.stop_gradient for t in op.inputs)
            if requires:
                outs, vjp_fn = jax.vjp(op.fn, *vals)
            else:
                outs = op.fn(*vals)
            out_list = list(outs) if op.multi else [outs]
            for t, v in zip(op.outputs, out_list):
                t._value = v
                t.grad = None
            if requires:
                autograd.record(autograd.Node(op.inputs, op.outputs,
                                              vjp_fn, op.multi))

        for optimizer, loss in program.train_hooks:
            if optimizer._parameter_list is None:
                # parameterless optimizer (standard static style): train
                # every trainable leaf of the program
                optimizer._parameter_list = program.all_parameters()
            loss.backward(retain_graph=True)
            optimizer._apply_params_grads(
                [(p, p.grad) for p in optimizer._parameter_list
                 if not p.stop_gradient and p.grad is not None])
            optimizer.clear_grad()
        # drop only the nodes this replay recorded — a caller's in-flight
        # eager graph on the same tape stays intact
        autograd.truncate_tape(tape_mark)

        if fetch_list is None:
            return []
        outs = []
        for f in fetch_list:
            t = program.placeholders.get(f) if isinstance(f, str) else f
            if not isinstance(t, Tensor):
                raise TypeError(f"cannot fetch {f!r}")
            outs.append(np.asarray(t._value) if return_numpy else t)
        return outs

    def _run_dataset(self, program, dataset, fetch_list, debug=False,
                     fetch_info=None, print_period=100, collect=False):
        """Shared batch driver for train/infer_from_dataset. Feed
        contract: every placeholder must be covered by the batch dict
        (checked on the first batch — a name mismatch must not silently
        train on the build-time zeros), and a short final batch (the
        drop_last=False tail) is SKIPPED with a warning: recorded ops
        bake the build-time batch shape."""
        results = []
        checked = False
        it = 0
        for batch in dataset:
            feed = {k: v for k, v in batch.items()
                    if k in program.placeholders}
            if not checked:
                missing = [n for n in program.placeholders if n not in feed]
                if missing:
                    raise KeyError(
                        f"dataset batches do not cover placeholder(s) "
                        f"{missing}; batch keys: {sorted(batch)}")
                checked = True
            short = [k for k, v in feed.items()
                     if np.shape(v) != tuple(
                         program.placeholders[k].shape)]
            if short:
                import warnings
                warnings.warn(
                    f"skipping dataset batch {it}: feed shapes for "
                    f"{short} differ from the program's build-time "
                    "shapes (set the dataset batch size to divide the "
                    "data, or use drop_last)", UserWarning)
                continue
            outs = self.run(program, feed=feed, fetch_list=fetch_list)
            if collect and fetch_list:
                results.append(outs)
            it += 1
            if debug and fetch_list and it % max(1, print_period) == 0:
                names = fetch_info or [str(f) for f in fetch_list]
                msg = ", ".join(f"{n}={np.asarray(o).ravel()[:1]}"
                                for n, o in zip(names, outs))
                print(f"[dataset run] batch {it}: {msg}")
        return results

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Drive a slot Dataset through the program's recorded train
        hooks, batch by batch (reference `executor.py
        train_from_dataset` -> `Executor::RunFromDataset`,
        `framework/executor.cc:152`, DeviceWorker::TrainFiles)."""
        if dataset is None:
            raise ValueError("train_from_dataset needs a dataset")
        self._run_dataset(program or _default_main, dataset, fetch_list,
                          debug, fetch_info, print_period)
        return None

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Inference twin of train_from_dataset (reference
        `infer_from_dataset`): replays WITHOUT running train hooks."""
        if dataset is None:
            raise ValueError("infer_from_dataset needs a dataset")
        program = program or _default_main
        saved = program.train_hooks
        program.train_hooks = []
        try:
            return self._run_dataset(program, dataset, fetch_list,
                                     debug, fetch_info, print_period,
                                     collect=True)
        finally:
            program.train_hooks = saved

    def close(self):
        pass


class CompiledProgram:
    """Fused-XLA execution of a recorded Program (the ParallelExecutor /
    BuildStrategy analog — here simply one jit over the replay)."""

    def __init__(self, program_or_graph, build_strategy=None):
        # reference param name (`fluid/compiler.py` CompiledProgram)
        self.program = program_or_graph
        self._jit_cache = {}
        self._leaves = None

    def _build(self, feed_names):
        program = self.program

        if self._leaves is None:
            # leaf inputs: tensors consumed before being produced
            produced, leaves = set(), []
            ph_ids = {id(t) for t in program.placeholders.values()}
            for op in program.ops:
                for t in op.inputs:
                    if id(t) not in produced and id(t) not in ph_ids and \
                            not any(t is l for l in leaves):
                        leaves.append(t)
                for t in op.outputs:
                    produced.add(id(t))
            self._leaves = leaves
        leaves = self._leaves

        def replay(feed_vals, leaf_vals, fetch_ids):
            env = {}
            for name, v in zip(feed_names, feed_vals):
                env[id(program.placeholders[name])] = v
            for t, v in zip(leaves, leaf_vals):
                env[id(t)] = v
            for op in program.ops:
                vals = tuple(env.get(id(t), t._value) for t in op.inputs)
                outs = op.fn(*vals)
                out_list = list(outs) if op.multi else [outs]
                for t, v in zip(op.outputs, out_list):
                    env[id(t)] = v
            return [env[i] for i in fetch_ids]

        return replay

    def run(self, feed, fetch_list):
        if self.program.train_hooks:
            raise NotImplementedError(
                "CompiledProgram replays forward ops only; run training "
                "programs (optimizer.minimize) through static.Executor")
        missing = [n for n in self.program.placeholders if n not in feed]
        if missing:
            raise KeyError(f"feed missing placeholders {missing}; their "
                           "build-time values would be baked in as constants")
        feed_names = sorted(feed)
        fetch_ids = tuple(id(t) for t in fetch_list)
        key = (tuple(feed_names), fetch_ids)
        jitted = self._jit_cache.get(key)
        if jitted is None:
            replay = self._build(feed_names)
            jitted = jax.jit(lambda fv, lv: replay(fv, lv, fetch_ids))
            self._jit_cache[key] = jitted
        feed_vals = [jnp.asarray(feed[n]) for n in feed_names]
        leaf_vals = [t._value for t in self._leaves]
        return [np.asarray(v) for v in jitted(feed_vals, leaf_vals)]


# re-exported conveniences (paddle.static namespace surface)
def name_scope(prefix=None):
    return contextlib.nullcontext()


class WeightNormParamAttr:
    def __init__(self, *a, **k):
        pass


class BuildStrategy:
    """Graph-build knobs (reference `details/build_strategy.h:75`). Every
    toggle the reference exposes — fusion passes, reduce strategy,
    sync_batch_norm, hierarchical allreduce — is owned by XLA/GSPMD here,
    so the attributes are accepted, recorded, and honestly inert; unknown
    names raise (a silently-absorbed typo would masquerade as tuning)."""

    _KNOWN = {
        "fuse_elewise_add_act_ops", "fuse_bn_act_ops", "fuse_bn_add_act_ops",
        "fuse_relu_depthwise_conv", "fuse_broadcast_ops",
        "fuse_all_optimizer_ops", "fuse_all_reduce_ops",
        "enable_auto_fusion", "enable_addto", "enable_inplace",
        "enable_sequential_execution", "cache_runtime_context",
        "memory_optimize", "sync_batch_norm", "reduce_strategy",
        "gradient_scale_strategy", "num_trainers",
        "trainer_id", "trainers_endpoints", "use_hierarchical_allreduce",
        "hierarchical_allreduce_inter_nranks", "fuse_grad_merge",
        "fuse_gemm_epilogue", "debug_graphviz_path", "nccl_comm_num",
        "mkldnn_enabled_op_types", "fix_op_run_order",
        "allow_cuda_graph_capture", "async_mode",
    }

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        object.__setattr__(self, "_values", {})

    def __setattr__(self, name, value):
        if name not in self._KNOWN:
            raise AttributeError(
                f"BuildStrategy has no knob {name!r} (XLA owns "
                "fusion/placement; accepted-for-compat knobs: "
                f"{sorted(self._KNOWN)})")
        self._values[name] = value

    def __getattr__(self, name):
        if name in type(self)._KNOWN:
            return self.__dict__["_values"].get(name)
        raise AttributeError(name)


class ExecutionStrategy:
    """Executor knobs (reference `execution_strategy.h`): thread counts and
    cleanup cadence have no analog under one fused XLA program; accepted
    and inert, same contract as BuildStrategy (typos rejected)."""

    _KNOWN = {"num_threads", "num_iteration_per_drop_scope",
              "num_iteration_per_run", "use_thread_barrier",
              "allow_op_delay", "use_device"}

    def __init__(self):
        object.__setattr__(self, "_values", {
            "num_threads": 1, "num_iteration_per_drop_scope": 1,
            "num_iteration_per_run": 1, "use_thread_barrier": False})

    def __setattr__(self, name, value):
        if name not in self._KNOWN:
            raise AttributeError(
                f"ExecutionStrategy has no knob {name!r}; known: "
                f"{sorted(self._KNOWN)}")
        self._values[name] = value

    def __getattr__(self, name):
        if name in type(self)._KNOWN:
            return self.__dict__["_values"].get(name)
        raise AttributeError(name)


from .compat import (  # noqa: F401,E402
    Variable, accuracy, auc, append_backward, gradients,
    create_parameter, create_global_var, cpu_places, cuda_places,
    xpu_places, global_scope, scope_guard, save, load, save_to_file,
    load_from_file, serialize_program, deserialize_program,
    serialize_persistables, deserialize_persistables,
    load_program_state, set_program_state, normalize_program,
    ExponentialMovingAverage, ParallelExecutor)
