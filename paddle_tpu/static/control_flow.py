"""Control-flow ops: while_loop / cond / case / switch_case.

TPU-native replacement for the reference's control-flow operators
(`python/paddle/fluid/layers/control_flow.py:973` While, `:2302` cond,
`:2551` case, `:2752` switch_case, backed by
`operators/controlflow/while_op.cc` and `conditional_block_op.cc` sub-block
execution). There is no sub-block interpreter here — three regimes map onto
what the hardware/compiler actually supports:

- **Eager (concrete values)**: plain Python control flow over Tensors. The
  autograd tape records whichever path ran, so loop-carried gradients work
  exactly like any other eager code (dygraph semantics).
- **Traced, no gradient needed**: `lax.while_loop` / `lax.cond` /
  `lax.switch` — compiled, lazy-branch, dynamic trip count. This is the
  path dynamic-length decoding uses under jit.
- **Traced, gradient needed**: XLA cannot reverse-differentiate an unbounded
  `while`; with `maximum_iterations` set, the loop lowers to a bounded,
  masked `lax.scan`, which IS differentiable. `cond`/`case` lower to a
  both-branches + `where` select so cotangents flow to both closures.

Shape/dtype invariance of loop_vars across iterations is required under
tracing (an XLA constraint the reference's While, running sub-programs on
host, did not have).
"""
import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.tensor import Tensor

__all__ = ["while_loop", "cond", "case", "switch_case", "Assert"]


def _flatten(vars_):
    return jax.tree_util.tree_flatten(
        vars_, is_leaf=lambda x: isinstance(x, Tensor))


def _is_traced(leaves):
    return any(isinstance(l._value if isinstance(l, Tensor) else l,
                          jax.core.Tracer) for l in leaves)


def _unwrap(leaves):
    return [l._value if isinstance(l, Tensor) else jnp.asarray(l)
            for l in leaves]


def _requires_grad(leaves):
    return autograd.grad_enabled() and any(
        isinstance(l, Tensor) and not l.stop_gradient for l in leaves)


def _scalar_bool(t):
    v = t._value if isinstance(t, Tensor) else t
    return jnp.reshape(jnp.asarray(v), ()).astype(jnp.bool_)


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               maximum_iterations=None):
    """paddle.static.nn.while_loop analog (`control_flow.py:973` While /
    `:1764` while_loop).

    cond(*loop_vars) -> scalar bool Tensor; body(*loop_vars) -> list of
    Tensors with the same structure/shapes/dtypes. Returns the final
    loop_vars list.
    """
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise TypeError("loop_vars must be a non-empty list/tuple")
    loop_vars = list(loop_vars)
    leaves, tree = _flatten(loop_vars)

    def norm_body_out(out):
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        if len(out) != len(loop_vars):
            raise ValueError(
                f"body returned {len(out)} vars, expected {len(loop_vars)}")
        return out

    if not _is_traced(leaves):
        # eager: honest Python loop; the tape sees every iteration
        while bool(cond(*loop_vars)):
            loop_vars = norm_body_out(body(*loop_vars))
        return loop_vars

    needs_grad = _requires_grad(leaves)

    def run_cond(vals):
        ts = [Tensor(v) for v in vals]
        return _scalar_bool(cond(*jax.tree_util.tree_unflatten(tree, ts)))

    def run_body(vals):
        ts = [Tensor(v) for v in vals]
        out = norm_body_out(body(*jax.tree_util.tree_unflatten(tree, ts)))
        out_leaves, out_tree = _flatten(out)
        return _unwrap(out_leaves)

    if not needs_grad:
        with autograd.no_grad():
            final = jax.lax.while_loop(run_cond, run_body, _unwrap(leaves))
        return [Tensor(v) for v in
                jax.tree_util.tree_unflatten(tree, list(final))]

    if maximum_iterations is None:
        raise ValueError(
            "while_loop under jit with gradients required needs "
            "maximum_iterations=N (lowers to a bounded differentiable scan); "
            "XLA cannot reverse-differentiate an unbounded while")

    # bounded masked scan: runs N steps, freezing loop_vars once cond is
    # False — reverse-differentiable. NOTE: gradients flow w.r.t. loop_vars
    # only; tensors merely captured by the body closure are constants to
    # this vjp — thread them through loop_vars if they need gradients.
    from ..core.tensor import apply

    def fn(*vals):
        def step(carry, _):
            with autograd.fresh_tape():  # suppress tape records inside scan
                vs = list(carry)
                done = jnp.logical_not(run_cond(vs))
                new = run_body(vs)
            vs2 = [jnp.where(done, v, n) for v, n in zip(vs, new)]
            return tuple(vs2), None
        out, _ = jax.lax.scan(step, tuple(vals), None,
                              length=int(maximum_iterations))
        return tuple(out)

    outs = apply(fn, *[l if isinstance(l, Tensor) else Tensor(l)
                       for l in leaves])
    outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    return jax.tree_util.tree_unflatten(tree, outs)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """paddle.static.nn.cond analog (`control_flow.py:2302`).

    true_fn/false_fn are nullary closures returning the same output
    structure.
    """
    pv = pred._value if isinstance(pred, Tensor) else pred
    if not isinstance(pv, jax.core.Tracer):
        taken = true_fn if bool(pv) else false_fn
        return taken() if taken is not None else None
    if true_fn is None or false_fn is None:
        raise ValueError("cond under jit needs both true_fn and false_fn")

    if autograd.grad_enabled():
        # differentiable select: run both branches on the tape, then blend.
        # Under XLA the untaken side is still computed (standard jit
        # trade-off); gradients flow into both closures' captures scaled by
        # the predicate mask.
        t_out = true_fn()
        f_out = false_fn()
        return _select_trees(_scalar_bool(pred), t_out, f_out)

    # forward-only: real lazy branches via lax.cond on raw values
    holder = {}

    def t_thunk(_):
        with autograd.fresh_tape(), autograd.no_grad():
            out = true_fn()
        leaves, tree = _flatten(out)
        holder["tree"] = tree
        return tuple(_unwrap(leaves))

    def f_thunk(_):
        with autograd.fresh_tape(), autograd.no_grad():
            out = false_fn()
        leaves, tree = _flatten(out)
        return tuple(_unwrap(leaves))

    vals = jax.lax.cond(_scalar_bool(pred), t_thunk, f_thunk, 0)
    return jax.tree_util.tree_unflatten(
        holder["tree"], [Tensor(v) for v in vals])


def _select_trees(pred_bool, t_out, f_out):
    t_leaves, tree = _flatten(t_out)
    f_leaves, _ = _flatten(f_out)
    if len(t_leaves) != len(f_leaves):
        raise ValueError("true_fn/false_fn must return the same structure")
    out = []
    for t, f in zip(t_leaves, f_leaves):
        tt = t if isinstance(t, Tensor) else Tensor(t)
        ff = f if isinstance(f, Tensor) else Tensor(f)
        from ..core.tensor import apply
        out.append(apply(
            lambda a, b: jnp.where(pred_bool, a, b.astype(a.dtype)), tt, ff))
    return jax.tree_util.tree_unflatten(tree, out)


def case(pred_fn_pairs, default=None, name=None):
    """paddle.static.nn.case analog (`control_flow.py:2551`): first pred
    that is True wins; `default` (or the last fn) otherwise."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    pairs = list(pred_fn_pairs)
    if default is None:
        pairs, (_, default) = pairs[:-1], pairs[-1]
        if not pairs:
            return default()
    out = default
    # build nested cond from the last pair outward so the FIRST true pred
    # takes priority
    for pred, fn in reversed(pairs):
        out = _bind_case(pred, fn, out)
    return out() if callable(out) else out


def _bind_case(pred, fn, else_branch):
    def branch():
        return cond(pred, fn,
                    else_branch if callable(else_branch)
                    else (lambda: else_branch))
    return branch


def switch_case(branch_index, branch_fns, default=None, name=None):
    """paddle.static.nn.switch_case analog (`control_flow.py:2752`)."""
    iv = branch_index._value if isinstance(branch_index, Tensor) \
        else branch_index
    if isinstance(branch_fns, (list, tuple)) and branch_fns and \
            not isinstance(branch_fns[0], (list, tuple)):
        fns = dict(enumerate(branch_fns))
    else:
        fns = dict(branch_fns)
    keys = sorted(fns)
    if default is None:
        default = fns[keys[-1]]
    if not isinstance(iv, jax.core.Tracer):
        return fns.get(int(iv), default)()

    # dense jump table for lax.switch: index -> position; any out-of-range
    # index (below min OR above max key) routes to the default slot, matching
    # the eager fns.get(i, default) semantics
    lo, hi = min(keys), max(keys)
    table = [fns.get(k, default) for k in range(lo, hi + 1)]
    table.append(default)
    raw = jnp.reshape(jnp.asarray(iv), ()).astype(jnp.int32)
    in_range = jnp.logical_and(raw >= lo, raw <= hi)
    idx = jnp.where(in_range, jnp.clip(raw - lo, 0, hi - lo), hi - lo + 1)

    if autograd.grad_enabled():
        # differentiable: select over all branches
        outs = [fn() for fn in table]
        result = outs[0]
        for j, o in enumerate(outs[1:], start=1):
            result = _select_trees(jnp.equal(idx, j), o, result)
        return result

    holder = {}

    def mk(fn):
        def thunk(_):
            with autograd.fresh_tape(), autograd.no_grad():
                out = fn()
            leaves, tree = _flatten(out)
            holder["tree"] = tree
            return tuple(_unwrap(leaves))
        return thunk

    vals = jax.lax.switch(idx, [mk(fn) for fn in table], 0)
    return jax.tree_util.tree_unflatten(
        holder["tree"], [Tensor(v) for v in vals])


def Assert(condition, data=None, summarize=20, name=None):
    """paddle.static.nn.control_flow.Assert analog: host-side check in
    eager; compiled-in `checkify`-style debug print under jit is out of
    scope, so traced asserts are no-ops (XLA has no abort op)."""
    cv = condition._value if isinstance(condition, Tensor) else condition
    if isinstance(cv, jax.core.Tracer):
        return
    if not bool(jnp.all(jnp.asarray(cv))):
        items = [] if data is None else [
            jnp.asarray(d._value if isinstance(d, Tensor) else d)
            for d in data]
        raise AssertionError(
            "Assert failed: " + ", ".join(str(i.ravel()[:summarize])
                                          for i in items))
