"""paddle.static.nn op-builders (reference `python/paddle/static/nn/` over
`fluid/layers/nn.py`): thin wrappers that create the corresponding Layer and
apply it, so legacy static model code builds under program_guard."""


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from ..nn import Linear
    from ..nn import functional as F
    from ..tensor.manipulation import reshape
    import numpy as np
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    if len(x.shape) > num_flatten_dims + 1:
        x = reshape(x, list(x.shape[:num_flatten_dims]) + [in_dim])
    layer = Linear(in_dim, size, weight_attr=weight_attr,
                   bias_attr=bias_attr)
    out = layer(x)
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """`use_cudnn` is the reference's CUDA kernel-choice hint
    (`fluid/layers/nn.py` conv2d); accepted for signature parity — on
    this backend every conv lowers through XLA, which owns kernel
    selection, so True and False compile identically (obviated, not
    dropped)."""
    from ..nn import Conv2D
    from ..nn import functional as F
    layer = Conv2D(input.shape[1], num_filters, filter_size, stride=stride,
                   padding=padding, dilation=dilation, groups=groups,
                   weight_attr=param_attr, bias_attr=bias_attr,
                   data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None,
               do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    """Reference `fluid/layers/nn.py` batch_norm signature (param order
    included: is_test sits after act). in_place is obviated (XLA owns
    buffer reuse); do_model_average_for_mean_and_var is obviated
    (ModelAverage here averages an explicit parameter list);
    moving_*_name label the running-stat tensors for state_dict keys;
    use_global_stats=True normalizes with the running statistics even
    in training, exactly like the reference."""
    from ..nn import BatchNorm2D
    from ..nn import functional as F
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                        weight_attr=param_attr, bias_attr=bias_attr,
                        data_format=data_layout,
                        use_global_stats=use_global_stats or None)
    if moving_mean_name:
        layer._mean.name = moving_mean_name
    if moving_variance_name:
        layer._variance.name = moving_variance_name
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """is_sparse/is_distributed are the reference's SelectedRows / PS
    placement hints (`fluid/input.py` embedding). Dense GSPMD embedding
    obviates both on this backend: sparse-grad tables live in the PS
    runtime instead (paddle_tpu.distributed.fleet SparseTable /
    csrc/pskv.cc), which is where is_distributed=True workloads land."""
    from ..nn import Embedding
    layer = Embedding(size[0], size[1], padding_idx=padding_idx,
                      weight_attr=param_attr)
    return layer(input)


# control flow (paddle.static.nn.while_loop etc. in the 2.x namespace)
from .control_flow import (while_loop, cond, case,  # noqa: F401,E402
                           switch_case)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    # use_cudnn: see conv2d — obviated CUDA kernel hint, kept for parity
    from ..nn import Conv3D
    from ..nn import functional as F
    layer = Conv3D(input.shape[1], num_filters, filter_size, stride=stride,
                   padding=padding, dilation=dilation, groups=groups,
                   weight_attr=param_attr, bias_attr=bias_attr,
                   data_format=data_format)
    out = layer(input)
    return getattr(F, act)(out) if act else out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    # use_cudnn: see conv2d — obviated CUDA kernel hint, kept for parity
    from ..nn import Conv2DTranspose
    from ..nn import functional as F
    layer = Conv2DTranspose(input.shape[1], num_filters, filter_size,
                            stride=stride, padding=padding,
                            dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format)
    out = layer(input, output_size=output_size)
    return getattr(F, act)(out) if act else out


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format="NCHW", name=None):
    from ..nn import functional as F
    if global_pooling:
        return F.adaptive_avg_pool2d(input, 1) if pool_type == "avg" \
            else F.adaptive_max_pool2d(input, 1)
    if pool_type == "avg":
        return F.avg_pool2d(input, pool_size, pool_stride, pool_padding,
                            ceil_mode=ceil_mode, exclusive=exclusive,
                            data_format=data_format)
    return F.max_pool2d(input, pool_size, pool_stride, pool_padding,
                        ceil_mode=ceil_mode, data_format=data_format)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ..nn import LayerNorm
    from ..nn import functional as F
    shape = list(input.shape[begin_norm_axis:])
    layer = LayerNorm(shape, epsilon=epsilon,
                      weight_attr=param_attr if scale else False,
                      bias_attr=bias_attr if shift else False)
    out = layer(input)
    return getattr(F, act)(out) if act else out


def group_norm(input, groups, epsilon=1e-05, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from ..nn import GroupNorm
    from ..nn import functional as F
    layer = GroupNorm(groups, input.shape[1], epsilon=epsilon,
                      weight_attr=param_attr, bias_attr=bias_attr,
                      data_format=data_layout)
    out = layer(input)
    return getattr(F, act)(out) if act else out


def instance_norm(input, epsilon=1e-05, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn import InstanceNorm2D
    layer = InstanceNorm2D(input.shape[1], epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    from ..nn import functional as F
    mode = ("downscale_in_infer"
            if dropout_implementation == "downgrade_in_infer"
            else "upscale_in_train")
    return F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


def prelu(x, mode="all", param_attr=None, name=None):
    from ..nn import PReLU
    n = 1 if mode == "all" else x.shape[1]
    return PReLU(num_parameters=n, weight_attr=param_attr)(x)


def one_hot(input, depth, allow_out_of_range=False):
    from ..nn import functional as F
    return F.one_hot(input, depth)


# sequence family (paddle.static.nn.sequence_* re-exports over the
# padded+lengths jagged representation — see tensor/sequence.py)
from ..tensor.sequence import (  # noqa: F401,E402
    sequence_pad, sequence_unpad, sequence_pool, sequence_softmax,
    sequence_concat, sequence_reverse, sequence_slice, sequence_erase,
    sequence_enumerate, sequence_conv, sequence_expand_as,
)

from .nn_extra import (  # noqa: F401,E402
    bilinear_tensor_product, conv3d_transpose, crf_decoding, data_norm,
    deform_conv2d, multi_box_head, nce, py_func, row_conv,
    sequence_expand, sequence_first_step, sequence_last_step,
    sequence_reshape, sequence_scatter, sparse_embedding, spectral_norm)
