"""paddle.static.nn op-builders (reference `python/paddle/static/nn/` over
`fluid/layers/nn.py`): thin wrappers that create the corresponding Layer and
apply it, so legacy static model code builds under program_guard."""


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from ..nn import Linear
    from ..nn import functional as F
    from ..tensor.manipulation import reshape
    import numpy as np
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    if len(x.shape) > num_flatten_dims + 1:
        x = reshape(x, list(x.shape[:num_flatten_dims]) + [in_dim])
    layer = Linear(in_dim, size, weight_attr=weight_attr,
                   bias_attr=bias_attr)
    out = layer(x)
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCHW"):
    from ..nn import Conv2D
    from ..nn import functional as F
    layer = Conv2D(input.shape[1], num_filters, filter_size, stride=stride,
                   padding=padding, dilation=dilation, groups=groups,
                   weight_attr=param_attr, bias_attr=bias_attr,
                   data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    from ..nn import BatchNorm2D
    from ..nn import functional as F
    layer = BatchNorm2D(input.shape[1], momentum=momentum, epsilon=epsilon)
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    from ..nn import Embedding
    layer = Embedding(size[0], size[1], padding_idx=padding_idx,
                      weight_attr=param_attr)
    return layer(input)


# control flow (paddle.static.nn.while_loop etc. in the 2.x namespace)
from .control_flow import (while_loop, cond, case,  # noqa: F401,E402
                           switch_case)
