"""Stdlib HTTP front for the serving engine.

Rides the PR-3 `telemetry.metrics_http.MetricsServer` pattern: a
threaded `http.server` endpoint with zero serving dependencies, so the
engine process is scrapeable and servable with nothing but the stdlib.

- **POST /generate** — body `{"prompt": [ids...], "max_new_tokens": N,
  "decode_strategy": "greedy"|"sampling", "top_k", "top_p",
  "temperature", "eos_token_id", "seed", "stream": bool}`.
  `stream=true` answers chunked `application/jsonl`: one
  `{"token": id}` line per generated token AS THE ENGINE EMITS IT
  (continuous batching means concurrent streams interleave at token
  granularity), then a `{"done": true, "tokens": [...]}` tail.
  `stream=false` blocks and answers `{"tokens": [...]}` once.
- **GET /metrics** — Prometheus text: the whole monitor registry,
  which includes the engine's `serving.*` gauges/counters (queue
  depth, KV-block utilization, preemptions, TTFT/TPOT p50/p99).
- **GET /healthz** — engine liveness + the serving.* snapshot.

    engine = ServingEngine(model, max_slots=8).start()
    srv = ServingHTTPServer(engine, port=8000).start()
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..telemetry.metrics_http import prometheus_text
from .scheduler import SamplingParams

__all__ = ["ServingHTTPServer"]


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-serving/1"
    protocol_version = "HTTP/1.1"

    def _send(self, code, body, ctype="application/json"):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        engine = self.server.engine
        if self.path == "/metrics":
            self._send(200, prometheus_text(),
                       ctype="text/plain; version=0.0.4; charset=utf-8")
        elif self.path in ("/", "/healthz"):
            body = {"status": "ok",
                    "serving": engine.metrics_snapshot()}
            self._send(200, json.dumps(body, indent=2, default=repr))
        else:
            self._send(404, json.dumps(
                {"error": f"unknown path {self.path!r}",
                 "endpoints": ["POST /generate", "/metrics", "/healthz"]}))

    def do_POST(self):
        if self.path != "/generate":
            self._send(404, json.dumps({"error": "POST /generate only"}))
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            prompt = req["prompt"]
            if not isinstance(prompt, list) or not prompt:
                raise ValueError("'prompt' must be a non-empty id list")
            params = SamplingParams(
                max_new_tokens=req.get("max_new_tokens", 32),
                decode_strategy=req.get("decode_strategy", "greedy"),
                top_k=req.get("top_k", 0),
                top_p=req.get("top_p", 1.0),
                temperature=req.get("temperature", 1.0),
                eos_token_id=req.get("eos_token_id"),
                seed=req.get("seed"))
            stream = bool(req.get("stream", False))
        except (KeyError, ValueError, TypeError,
                json.JSONDecodeError) as e:
            self._send(400, json.dumps({"error": str(e)}))
            return
        try:
            handle = self.server.engine.submit([int(t) for t in prompt],
                                               params)
        except ValueError as e:       # over-length request etc.
            self._send(429, json.dumps({"error": str(e)}))
            return
        if not stream:
            try:
                toks = handle.result(timeout=self.server.request_timeout)
            except Exception as e:
                self._send(500, json.dumps({"error": str(e)}))
                return
            self._send(200, json.dumps({"tokens": toks,
                                        "stats": handle.stats}))
            return
        # chunked token stream: one JSON line per token as it lands
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonl")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(obj):
            data = (json.dumps(obj) + "\n").encode()
            self.wfile.write(f"{len(data):x}\r\n".encode() + data
                             + b"\r\n")
            self.wfile.flush()

        try:
            toks = []
            for tok in handle.tokens(timeout=self.server.request_timeout):
                toks.append(tok)
                chunk({"token": tok})
            chunk({"done": True, "tokens": toks, "stats": handle.stats})
        except Exception as e:
            chunk({"error": str(e)})
        self.wfile.write(b"0\r\n\r\n")

    def log_message(self, fmt, *args):
        pass


class ServingHTTPServer:
    """Threaded HTTP endpoint over a running ServingEngine. start() is
    non-blocking; the engine's own loop thread does the work."""

    def __init__(self, engine, host="127.0.0.1", port=0,
                 request_timeout=300.0):
        self.engine = engine
        self.host = host
        self.port = int(port)
        self.request_timeout = float(request_timeout)
        self._httpd = None
        self._thread = None

    def start(self):
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        httpd.daemon_threads = True
        httpd.engine = self.engine
        httpd.request_timeout = self.request_timeout
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="paddle-tpu-serving-http",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
